"""Decode program cache: one compiled step per serving configuration.

The serving hot path dispatches the SAME program millions of times; what
varies between deployments is the model, the batch bucket, the page
budget, the dtype, and the flag settings. This module keys compiled
decode steps on exactly that tuple so:

  - a re-created :class:`~paddle_tpu.generation.serving.ServingEngine`
    over the same model re-uses the already-compiled step (no retrace on
    re-admission — jax.jit caches per *callable*, so a fresh engine
    building a fresh closure used to recompile from scratch);
  - ``fused_multi_transformer`` / ``masked_multihead_attention`` decode
    calls run one cached compiled program instead of dispatching their
    op chains eagerly per token;
  - flag resolution happens ONCE at program-build time (the flag tuple
    is part of the key), never per decode step.

Keys are structural — a model's signature is its class plus the
name/shape/dtype tree of its state — so two same-config model instances
share one program; the weights always travel as traced arguments, never
as baked-in constants.

Lifetime note: the cache never evicts. The fused decode step is a pure
function of its param dicts, but the GENERIC and PREFILL builders close
over the model object (functional_call needs the Layer structure), so a
cached generic program keeps that model — weights included — alive for
the process. A serving process that retires a model and loads a
replacement should call :func:`clear_decode_program_cache` (the
replacement re-compiles once and re-caches).

Every cached program carries a trace probe: the builder receives a
``note_trace`` callback to call INSIDE the traced python body, which
executes only when jax actually (re)traces. ``trace_count(key)`` is the
retrace regression test surface (the acceptance criterion "zero retraces
across repeated step() calls" asserts it stays at 1).

Telemetry (``FLAGS_telemetry``): hits/misses/traces mirror onto the
process metrics registry, and every dispatch that (re)traced is charged
its full wall clock to a per-kind compile-time histogram — a retrace
regression shows up with a COST attached, not just a count. The timing
wrapper exists only when telemetry is on; off, ``get`` returns the bare
compiled callable (zero added work per decode step).

Memwatch (``FLAGS_memwatch``, riding the telemetry gate): the same
wrapper banks every (re)traced program's ``CompiledMemoryStats`` into
``program_memory_bytes{kind,bucket,extra,section}`` — each cached
program carries a memory signature next to its compile-time counter
(see ``paddle_tpu/observability/memory.py``).
"""

from __future__ import annotations

import hashlib
import re
import threading
import time
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

__all__ = ["DecodeKey", "DecodeProgramCache", "decode_program_cache",
           "clear_decode_program_cache", "model_signature"]


class DecodeKey(NamedTuple):
    """(model signature, batch bucket, page budget, dtype, flag tuple) —
    plus ``kind`` to separate the program families sharing the cache and
    ``extra`` for kind-specific geometry (the chunked-prefill programs
    key on their chunk length here; empty for the classic kinds)."""
    kind: str                 # decode_fused | decode_generic | prefill | ...
    model_sig: str
    batch_bucket: int
    page_budget: Tuple        # (num_pages, page_size, max_pages_per_seq)
    dtype: str
    flags: Tuple              # flags.snapshot(...).as_tuple()
    extra: Tuple = ()         # kind-specific, e.g. (chunk_len,)


# default object.__repr__ embeds a memory address: "<X object at 0x7f..>"
_ADDR_RE = re.compile(r"0x[0-9a-fA-F]+")


def model_signature(model) -> str:
    """Structural identity of a model: class + config + the full
    name/shape/dtype tree of params and buffers, digested. Captures
    everything that changes the traced program; weight VALUES are traced
    arguments and deliberately excluded.

    The config repr is canonicalized: a config member with a default
    ``object.__repr__`` embeds its memory address, which would mint a
    DISTINCT signature per instance — silently defeating cross-engine
    program sharing and splitting telemetry ``model`` labels. Addresses
    carry no structural identity, so they are zeroed out of the repr."""
    cfg_repr = _ADDR_RE.sub("0x0", repr(getattr(model, "config", None)))
    parts = [type(model).__name__, cfg_repr,
             f"training={getattr(model, 'training', False)}"]
    for name, t in sorted(model.named_parameters()):
        parts.append(f"{name}:{tuple(t.shape)}:{t.dtype}")
    for name, t in sorted(model.named_buffers()):
        if t is not None:
            parts.append(f"b:{name}:{tuple(t.shape)}:{t.dtype}")
    return hashlib.md5("|".join(parts).encode()).hexdigest()


def _key_tp(key: DecodeKey) -> str:
    """Tensor-parallel degree a key was built under, as a label value.
    The degree rides ``extra`` as a ``("tp", n)`` pair ONLY when the
    engine is armed (tp > 1), so every tp=1 key — and every pre-tp key —
    resolves to the default "1" without a schema change."""
    for item in key.extra:
        if isinstance(item, tuple) and len(item) == 2 and item[0] == "tp":
            return str(item[1])
    return "1"


class DecodeProgramCache:
    """Thread-safe keyed cache of compiled decode steps with per-key
    trace counting."""

    def __init__(self):
        from .. import observability as obs
        from ..testing import faults

        # build-path fault injection (FLAGS_fault_inject
        # 'program_build:...'): bound at cache construction; use
        # clear_decode_program_cache() to re-arm after a flag change
        self._f_build = faults.site("program_build")
        self._lock = threading.Lock()
        self._programs: Dict[DecodeKey, Any] = {}
        self._trace_counts: Dict[DecodeKey, int] = {}
        # per-key mutable trace cell [count]: the dispatch timing wrapper
        # reads it lock-free to detect "this call (re)traced"
        self._trace_cells: Dict[DecodeKey, List[int]] = {}
        self._compile_seconds: Dict[DecodeKey, float] = {}
        self.hits = 0
        self.misses = 0
        self._telemetry = obs.enabled()
        # memwatch (FLAGS_memwatch, riding the telemetry gate): a
        # dispatch that (re)traced additionally banks the program's
        # CompiledMemoryStats — one duplicate lower+compile at exactly
        # the moment the compile-seconds histogram already charges
        self._memwatch = self._telemetry and obs.memory.enabled()
        if self._telemetry:
            r = obs.registry()
            self._m_hits = r.counter(
                "program_cache_hits",
                "decode program cache admissions served from cache")
            self._m_misses = r.counter(
                "program_cache_misses",
                "decode program cache admissions that built a program")
            self._m_traces = r.counter(
                "program_cache_traces",
                "jax (re)traces of cached programs (steady state: one "
                "per key); model = signature prefix, so two models' "
                "programs — or a fleet serving several — never share "
                "a series; tp = tensor-parallel degree from the key "
                "(\"1\" unless the engine sharded the program)",
                labels=("kind", "model", "tp"))
            self._m_compile = r.histogram(
                "program_cache_compile_seconds",
                "wall clock of dispatches that (re)traced — trace + "
                "compile cost per program kind, model and tp degree",
                labels=("kind", "model", "tp"))
        else:
            self._m_hits = self._m_misses = obs.NULL
            self._m_traces = self._m_compile = obs.NULL

    def get(self, key: DecodeKey,
            builder: Callable[[Callable[[], None]], Any]):
        """Return the compiled step for ``key``, building it on first
        use. ``builder(note_trace)`` must return the (jitted) callable
        and arrange for ``note_trace()`` to run inside the traced body —
        it then fires exactly once per (re)trace."""
        with self._lock:
            fn = self._programs.get(key)
            if fn is not None:
                self.hits += 1
                self._m_hits.inc()
                return fn
        self._f_build.check(kind=key.kind)   # injected build failure
        fn = builder(self._tracer(key))      # may be slow: build unlocked
        if self._telemetry:
            fn = self._timed_dispatch(key, fn)
        with self._lock:
            cur = self._programs.setdefault(key, fn)
            if cur is fn:
                self.misses += 1
                self._m_misses.inc()
            else:
                self.hits += 1               # lost a benign build race
                self._m_hits.inc()
            return cur

    def _tracer(self, key: DecodeKey) -> Callable[[], None]:
        with self._lock:
            cell = self._trace_cells.setdefault(key, [0])

        def note_trace():
            # runs INSIDE the traced python body, so it fires exactly
            # once per (re)trace — a host-side trace-TIME write, which
            # is the deliberate exception to "no telemetry under trace"
            cell[0] += 1
            with self._lock:
                self._trace_counts[key] = self._trace_counts.get(key, 0) + 1
            self._m_traces.labels(kind=key.kind,
                                  model=key.model_sig[:8],
                                  tp=_key_tp(key)).inc()
        return note_trace

    def _timed_dispatch(self, key: DecodeKey, fn):
        """Wrap a compiled step so any dispatch that (re)traced is
        charged its wall clock to the compile histogram — and, with
        memwatch on, banks the program's CompiledMemoryStats (an AOT
        lower+compile over the SAME avals: donation only invalidates
        buffers, avals survive, so this is safe post-dispatch and each
        retrace re-captures with the args that caused it). Steady-state
        cost: one list read + two perf_counter calls per step (~100 ns
        against a ~ms decode step)."""
        from .. import observability as obs

        with self._lock:
            cell = self._trace_cells.setdefault(key, [0])
        hist = self._m_compile.labels(kind=key.kind,
                                      model=key.model_sig[:8],
                                      tp=_key_tp(key))

        def dispatch(*args, **kwargs):
            before = cell[0]
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            if cell[0] != before:
                dt = time.perf_counter() - t0
                hist.observe(dt)
                with self._lock:
                    self._compile_seconds[key] = (
                        self._compile_seconds.get(key, 0.0) + dt)
                if self._memwatch:
                    obs.memory.capture_program(
                        key.kind, key.batch_bucket, key.extra,
                        fn, args, kwargs, model=key.model_sig[:8])
            return out

        return dispatch

    def trace_count(self, key: DecodeKey) -> int:
        with self._lock:
            return self._trace_counts.get(key, 0)

    def compile_seconds(self, key: DecodeKey) -> float:
        """Accumulated trace+compile wall clock banked for ``key``
        (0.0 with telemetry off — the timing wrapper is not installed)."""
        with self._lock:
            return self._compile_seconds.get(key, 0.0)

    def keys(self) -> List[DecodeKey]:
        """Every key with a cached program (admission order) — the live
        census ``tools/telemetry_dump.py --programs`` renders."""
        with self._lock:
            return list(self._programs)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "programs": len(self._programs),
                    "traces": dict(self._trace_counts),
                    "compile_seconds": dict(self._compile_seconds)}

    def clear(self) -> None:
        with self._lock:
            self._programs.clear()
            self._trace_counts.clear()
            self._trace_cells.clear()
            self._compile_seconds.clear()
            self.hits = self.misses = 0


_GLOBAL: Optional[DecodeProgramCache] = None
_GLOBAL_LOCK = threading.Lock()


def decode_program_cache() -> DecodeProgramCache:
    """The process-wide decode program cache."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = DecodeProgramCache()
        return _GLOBAL


def clear_decode_program_cache() -> None:
    """Drop every cached program AND the cache instance itself, so the
    next :func:`decode_program_cache` call rebinds telemetry under the
    current ``FLAGS_telemetry`` setting."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is not None:
            _GLOBAL.clear()
        _GLOBAL = None
