"""Continuous-batching serving engine over the paged KV cache.

Reference parity target: the reference ecosystem's block-attention
serving runtime (PaddleNLP llm serving over block_multihead_attention /
the vLLM scheduler design): requests ADMIT into free batch slots the
moment one opens, every decode step runs the whole fixed-shape batch with
per-slot ragged lengths, and finished sequences return their pages to the
shared pool for the next request.

TPU-native structure: exactly TWO compiled programs serve steady state —
a b=1 prefill per distinct prompt length (bucketable) and ONE fixed-shape
decode step over max_batch slots. Ragged per-slot positions ride the
paged kernel's seq_lens; idle slots write into the reserved null page and
their outputs are ignored. The host loop between tokens is where the
scheduler lives — admission, eviction, and result collection are plain
Python on block tables.

Greedy decoding (the deterministic serving mode); sampling composes the
same way via the logits hook.
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import observability as obs
from ..analysis import key_vocab
from ..kernels.paged_attention import PagedDecodeState, PagedKVCache
from ..testing import faults

__all__ = ["ServingEngine", "Request"]

# terminal request statuses (Request.status / ServingEngine.status)
OK, FAILED, TIMEOUT = "OK", "FAILED", "TIMEOUT"


@dataclass
class Request:
    rid: int
    prompt: np.ndarray                  # (P,) int32
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    tokens: List[int] = field(default_factory=list)
    slot: Optional[int] = None
    # prompt-suffix tokens still to be teacher-forced through the decode
    # step (prefix-cache admission skipped their prefill)
    pending: List[int] = field(default_factory=list)
    # prefix-cache pages this request adopted (pinned until it finishes)
    pinned: List[int] = field(default_factory=list)
    # telemetry lifecycle stamps (perf_counter): submit time and the
    # last generated-token time (inter-token latency baseline)
    t_submit: float = 0.0
    t_last: float = 0.0
    # absolute perf_counter cutoff (submit(deadline=...)); enforced at
    # step boundaries — None = no deadline
    deadline: Optional[float] = None
    # terminal status ("PENDING" while queued/in flight)
    status: str = "PENDING"
    error: Optional[str] = None
    # replay-recovery bookkeeping: consecutive no-progress replays, and
    # the (tokens, prefill-cursor) high-water mark at the last failure
    # (progress on EITHER axis resets the budget — a long prompt's
    # chunks are progress before any token exists)
    retries: int = 0
    progress_mark: Tuple[int, int] = (-1, -1)
    # chunked-prefill cursor: tokens of ``feed`` already written to the
    # KV pool (None = not mid-prefill); ``feed`` is the teacher-forced
    # token stream (prompt, plus emitted tokens on replay)
    prefill_pos: Optional[int] = None
    feed: Optional[np.ndarray] = None
    # streaming callbacks deliberately do NOT live on the request: they
    # are engine-local state (``ServingEngine._callbacks``, rid ->
    # on_token), stripped at every export seam and re-bound on
    # inject/adopt — a bound callable inside a handoff bundle cannot
    # cross the process boundary the fleet transport serializes over
    # prefix-aware admission bookkeeping: how many cached-prefix
    # requests bypassed THIS request while it was the page-blocked head
    bypassed: int = 0
    # SLO preemption bookkeeping: times this request was unseated for a
    # tighter-deadline arrival (bounded by FLAGS_serving_preempt_budget;
    # never counts against the replay-recovery retry budget)
    preempts: int = 0
    # ---- speculative decoding (r16) ---------------------------------
    # sampling law (temperature 0 = greedy); temperature > 0 requires a
    # draft-model engine — the spec verify program is the only sampler
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: Optional[int] = None
    # per-request adaptive draft length: current γ rung (0 = none yet)
    # and the accept-rate EMA that moves it. Both SURVIVE replay — the
    # draft's observed agreement is a property of the request's text,
    # not of the admission that learned it
    gamma: int = 0
    spec_ema: float = 0.5
    # transient: the draft pool holds this slot's allocation (the draft
    # KV cursor itself is the draft pool's seq_lens row)
    spec_ready: bool = False


_POOL_STATES = ("used", "free", "shared", "pinned", "spilled")

# schema version of the harvest_request/adopt_request handoff bundle:
# bumped whenever the bundle's field set changes, and validated at
# adopt — a disaggregated pair built from different revisions must
# refuse loudly instead of mis-seating pages
HANDOFF_SCHEMA_VERSION = 1


class _EngineTelemetry:
    """Pre-bound instrument handles for the serving hot path: resolved
    once per engine, one attribute read per write inside ``step()`` —
    no registry lookups, no flag reads per token.

    Every family carries a ``replica`` label (r14): two engines in one
    process — the fleet case — used to collide on one series, so one
    replica's TTFT polluted another's and the KV gauges flapped between
    pools. The label is threaded from the engine's ``replica`` id and
    each engine binds its own child instruments here, once.

    Every family also carries a ``tp`` label (r19, the tensor-parallel
    degree, "1" for a solo engine): one FLT005-clean schema per family
    everywhere it is registered, so a tp=2 engine's series never merge
    with a solo replica's in a mixed fleet."""

    enabled = True

    def __init__(self, replica: str = "0", tp: str = "1"):
        r = obs.registry()
        t = obs.tracer()
        rl = ("replica", "tp")

        def c(name, help):
            return r.counter(name, help,
                             labels=rl).labels(replica=replica, tp=tp)

        def g(name, help):
            return r.gauge(name, help,
                           labels=rl).labels(replica=replica, tp=tp)

        def h(name, help):
            return r.histogram(name, help,
                               labels=rl).labels(replica=replica, tp=tp)

        self.span = t.span
        self.event = t.event
        self.submitted = c(
            "serving_requests_submitted", "requests accepted by submit()")
        self.finished = c(
            "serving_requests_finished", "requests that completed")
        self.prefills = c(
            "serving_prefills", "b=1 prefill programs dispatched")
        self.shared_admits = c(
            "serving_shared_admissions",
            "admissions that adopted cached prefix pages (prefill skipped)")
        self.decode_steps = c(
            "serving_decode_steps", "full-batch decode steps dispatched")
        self.ttft = h(
            "serving_ttft_seconds",
            "time to first generated token, submit() to host-visible")
        self.itl = h(
            "serving_inter_token_seconds",
            "per-request latency between consecutive generated tokens")
        self.queue_depth = g(
            "serving_queue_depth", "requests waiting for a batch slot")
        self.occupancy = g(
            "serving_batch_occupancy",
            "active slots in the fixed-shape decode batch")
        self.kv_pages_in_use = g(
            "serving_kv_pages_in_use",
            "KV pool pages held by sequences or the prefix cache "
            "(excludes the reserved null page)")
        self.prefix_pinned = g(
            "serving_prefix_pinned_pages",
            "prefix-cache pages pinned by in-flight requests — the "
            "pressure that caps evict() reclaim")
        self.evict_short = c(
            "serving_prefix_evict_shortfall_pages",
            "pages evict() was asked for but could not free "
            "(pinned/shared)")
        # ---- fault-tolerance instruments (replay recovery, r10)
        self.retries = c(
            "serving_retries_total",
            "in-flight request replays re-queued by recovery after a "
            "failed dispatch")
        self.recoveries = c(
            "serving_recoveries",
            "replay-recovery events: failed dispatch -> fresh pools + "
            "re-queue of all in-flight requests")
        self.requests_failed = c(
            "serving_requests_failed",
            "requests terminated FAILED (no-progress retry budget "
            "exhausted)")
        self.requests_timeout = c(
            "serving_requests_timeout",
            "requests terminated TIMEOUT (per-request deadline or the "
            "run(max_wall=...) watchdog)")
        self.recovery_seconds = h(
            "serving_recovery_seconds",
            "wall clock of one replay recovery (fresh pools + requeue, "
            "excluding backoff sleep)")
        self.page_pressure = g(
            "serving_page_pressure",
            "KV pages short at the last page-blocked admission (0 = "
            "admission is not page-blocked)")
        # ---- continuous-batching instruments (chunked prefill +
        # bucket ladder, r12)
        self.prefill_chunk_s = h(
            "serving_prefill_chunk_seconds",
            "wall clock of one chunked-prefill chunk dispatch — the "
            "bound on how long a long-prompt arrival can stall decode")
        self.decode_stall_s = h(
            "serving_decode_stall_seconds",
            "per-step wall clock decoding slots spent waiting on "
            "scheduler + prefill work before the decode dispatch "
            "(observed only on steps that ran prefill work while "
            "decode-ready requests were waiting)")
        self.bucket = g(
            "serving_bucket",
            "current decode batch-bucket rung of the bucket ladder")
        self.migrations = c(
            "serving_bucket_migrations",
            "bucket-ladder migrations (grow or shrink) — each rung's "
            "program compiles once, so steady state stops migrating "
            "or cycles between already-compiled rungs")
        # ---- SLO-aware preemption (r14)
        self.preemptions = c(
            "serving_preemptions",
            "running requests unseated for a tighter-deadline arrival "
            "and re-queued for bit-identical replay from host state")
        self.preempted_tokens = c(
            "serving_preempted_tokens_replayed",
            "decode tokens preemption victims will regenerate on "
            "replay — the compute a preemption trades for deadline "
            "slack")
        # ---- speculative decoding (r16)
        self.spec_rounds_c = c(
            "serving_spec_rounds",
            "speculation rounds retired (one draft-propose scan + one "
            "target-verify chunk per round)")
        self.spec_accept = h(
            "serving_spec_accept_rate",
            "per-round fraction of draft proposals the target verify "
            "accepted — the signal per-request adaptive γ follows")
        self.spec_accepted = c(
            "serving_spec_tokens_accepted",
            "draft-proposed tokens the target verify accepted")
        self.spec_rejected = c(
            "serving_spec_tokens_rejected",
            "draft-proposed tokens the target verify rejected — their "
            "KV positions rolled back to the accepted length and the "
            "next dispatch overwrites them")
        self.spec_gamma = g(
            "serving_spec_gamma",
            "γ (draft tokens per round) of the most recent speculation "
            "round: per-request adaptive within the "
            "FLAGS_serving_spec_rungs set, capped down as batch "
            "occupancy prices speculation out")
        # ---- tensor-parallel decode (r19)
        self.collective_s = h(
            "serving_collective_seconds",
            "wall clock of one tensor-parallel sharded decode dispatch "
            "(per-layer psum pair + compute), observed host-side at the "
            "dispatch boundary — only tp > 1 engines write it")
        # ---- memwatch pool ledger (r13): step-end gauges over the
        # PagedKVCache ledger, pre-resolved per state label; "spilled"
        # (r14) is the host-RAM tier
        pages = r.gauge(
            "kv_pool_pages",
            "KV page-pool ledger by state: used (held by sequences or "
            "the prefix cache), free, shared (refcount > 1), pinned "
            "(prefix pages an in-flight request's block table holds), "
            "spilled (prefix pages resident only in the host-RAM tier)",
            labels=("replica", "tp", "state"))
        pbytes = r.gauge(
            "kv_pool_bytes",
            "KV page-pool ledger in bytes (all layers, k+v)",
            labels=("replica", "tp", "state"))
        self.pool_pages = {s: pages.labels(replica=replica, tp=tp, state=s)
                           for s in _POOL_STATES}
        self.pool_bytes = {s: pbytes.labels(replica=replica, tp=tp,
                                            state=s)
                           for s in _POOL_STATES}
        self.pool_frag = g(
            "kv_pool_fragmentation",
            "free-list fragmentation: 1 - largest contiguous free run "
            "/ free pages (0 = clean; recomputed only when the free "
            "list changed)")
        self.host_tier_peak = g(
            "kv_host_tier_peak_pages",
            "high-water mark of pages resident in the host-RAM KV "
            "tier — the tier watermark memwatch prices against host "
            "memory")
        self.counter_track = t.counter


class _NullEngineTelemetry:
    """FLAGS_telemetry=0 binding: every write is a no-op method call."""

    enabled = False

    def __init__(self, replica: str = "0", tp: str = "1"):
        self.span = obs.null_span
        self.event = obs.null_event
        self.submitted = self.finished = self.prefills = obs.NULL
        self.shared_admits = self.decode_steps = obs.NULL
        self.ttft = self.itl = obs.NULL
        self.queue_depth = self.occupancy = obs.NULL
        self.kv_pages_in_use = self.prefix_pinned = obs.NULL
        self.evict_short = obs.NULL
        self.retries = self.recoveries = obs.NULL
        self.requests_failed = self.requests_timeout = obs.NULL
        self.recovery_seconds = self.page_pressure = obs.NULL
        self.prefill_chunk_s = self.decode_stall_s = obs.NULL
        self.bucket = self.migrations = obs.NULL
        self.preemptions = self.preempted_tokens = obs.NULL
        self.spec_rounds_c = self.spec_accept = obs.NULL
        self.spec_accepted = self.spec_rejected = obs.NULL
        self.spec_gamma = self.collective_s = obs.NULL
        self.pool_pages = {s: obs.NULL for s in _POOL_STATES}
        self.pool_bytes = {s: obs.NULL for s in _POOL_STATES}
        self.pool_frag = self.host_tier_peak = obs.NULL
        self.counter_track = obs.null_counter


class _PrefixTelemetry:
    enabled = True

    def __init__(self, replica: str = "0"):
        r = obs.registry()
        rl = ("replica",)

        def c(name, help):
            return r.counter(name, help, labels=rl).labels(replica=replica)

        self.hits = c(
            "prefix_cache_hits", "lookups that matched >= 1 cached page")
        self.misses = c(
            "prefix_cache_misses", "lookups that matched nothing")
        self.hit_pages = c(
            "prefix_cache_hit_pages", "cached pages returned by lookups")
        self.registered_pages = c(
            "prefix_cache_registered_pages",
            "new prompt pages registered into the trie")
        self.evicted_pages = c(
            "prefix_cache_evicted_pages",
            "pages actually returned to the free list by evict()")
        # ---- host-RAM tiering (r14)
        self.spilled_pages = c(
            "prefix_cache_spilled_pages",
            "cold prefix pages spilled to the host-RAM tier (device "
            "page freed, KV bytes retained host-side)")
        self.restored_pages = c(
            "prefix_cache_restored_pages",
            "spilled prefix pages paged back onto the device on "
            "prefix adoption")
        self.dropped_spilled = c(
            "prefix_cache_dropped_spilled_pages",
            "spilled pages evicted from the host tier entirely "
            "(host-tier budget pressure)")


class _NullPrefixTelemetry:
    enabled = False

    def __init__(self, replica: str = "0"):
        self.hits = self.misses = self.hit_pages = obs.NULL
        self.registered_pages = self.evicted_pages = obs.NULL
        self.spilled_pages = self.restored_pages = obs.NULL
        self.dropped_spilled = obs.NULL


class PrefixCache:
    """Page-aligned prompt-prefix trie over a :class:`PagedKVCache`
    (reference parity target: the vLLM-style automatic prefix caching in
    the reference's serving ecosystem).

    Each node maps one FULL page of prompt tokens (keyed by its parent
    chain, so equal chunks under different prefixes never collide) to the
    page id holding that chunk's KV. Registered pages carry a cache
    reference, so they survive their creating request and later requests
    with the same prefix adopt them read-only instead of re-running
    prefill. Causality makes this sound: KV at position i depends only on
    tokens 0..i, so equal page-aligned prefixes have bitwise-equal pages.
    Eviction drops least-recently-used LEAF nodes only (an interior node
    must outlive its children or their chains become unreachable).

    Host-RAM tiering (r14, ``host_tier_pages`` > 0): eviction pressure
    first SPILLS cold nodes — device page copied to host RAM
    (:meth:`PagedKVCache.spill_page`) and returned to the free list,
    trie node kept with the host copy — and ``lookup`` pages spilled
    chain nodes back in on adoption (one restore write beats re-running
    the chunk's prefill compute). Spill candidates come straight from
    the r13 ledger states: only pages the cache alone references
    (rc == 1, i.e. not ``shared`` with a live sequence) and that no
    in-flight request pins; when free-list fragmentation is high the
    policy prefers spilling pages adjacent to free runs, so spills heal
    the free list instead of shredding it further. Past the host-tier
    budget the coldest spilled LEAF nodes drop entirely (classic
    eviction)."""

    _ROOT = ("root",)

    def __init__(self, pool: PagedKVCache, replica: str = "0",
                 host_tier_pages: int = 0):
        self.pool = pool
        self.page_size = pool.page_size
        self.host_tier_pages = int(host_tier_pages)
        # key -> {"page": int|None, "parent": key, "children": int,
        #         "tick": int, "pins": int, "host": HostPage|None}
        # (page is None exactly while the node is spilled)
        self._nodes: Dict[tuple, dict] = {}
        self._by_page: Dict[int, tuple] = {}    # page id -> node key
        self._tick = 0
        self._pinned_nodes = 0      # nodes with pins > 0 (O(1) gauge)
        self._spilled_nodes = 0     # nodes in the host tier (O(1))
        self._f_spill = faults.site("kv_spill")
        self._m = (_PrefixTelemetry(replica) if obs.enabled()
                   else _NullPrefixTelemetry(replica))

    def _chunks(self, prompt: np.ndarray):
        key = self._ROOT
        for i in range(0, (len(prompt) // self.page_size) * self.page_size,
                       self.page_size):
            chunk = prompt[i:i + self.page_size].tobytes()
            key = (key, chunk)
            yield key

    def lookup(self, prompt: np.ndarray, max_cover: Optional[int] = None):
        """Longest cached page-aligned prefix: (page_ids, n_tokens).
        Spilled chain nodes are paged back in from the host tier when a
        free device page exists (restore is one pool write; the
        alternative is re-running the chunk's prefill compute); the hit
        ends at the first spilled node that cannot be restored.
        ``max_cover`` caps the returned coverage in tokens — the engine
        passes ``len(prompt) - 1`` because it can never adopt a
        whole-prompt hit (the first generated token's logits are not
        cached), and a restore spent on a page the caller then discards
        would consume a free page for nothing."""
        self._tick += 1
        pages: List[int] = []
        for key in self._chunks(prompt):
            if max_cover is not None and \
                    (len(pages) + 1) * self.page_size > max_cover:
                break           # the caller could not adopt this page
            node = self._nodes.get(key)
            if node is None:
                break
            if node["host"] is not None:
                if self.pool.free_page_count() == 0:
                    break       # no room to page in: hit ends here
                self._restore_node(key, node)
            node["tick"] = self._tick
            pages.append(node["page"])
        if pages:
            self._m.hits.inc()
            self._m.hit_pages.inc(len(pages))
        else:
            self._m.misses.inc()
        return pages, len(pages) * self.page_size

    def _restore_node(self, key: tuple, node: dict) -> None:
        """Page one spilled node back onto the device: fresh page off
        the free list, host bytes written back, cache reference
        restored. The fault check runs BEFORE any mutation, so an
        injected restore failure leaves the tier consistent and simply
        propagates into replay recovery."""
        self._f_spill.check(op="restore")
        pid = self.pool.take_free_page()
        self.pool.restore_page(node["host"], pid)
        node["host"] = None
        node["page"] = pid
        self._by_page[pid] = key
        self._spilled_nodes -= 1
        self._m.restored_pages.inc()

    def register(self, prompt: np.ndarray, block_row) -> None:
        """Pin the full prompt pages of a just-prefilled sequence."""
        self._tick += 1
        for i, key in enumerate(self._chunks(prompt)):
            node = self._nodes.get(key)
            if node is not None:        # dedup: keep the existing page
                node["tick"] = self._tick
                if node["host"] is not None:
                    # the just-prefilled sequence re-materialized this
                    # chunk's KV on device (equal page-aligned prefixes
                    # are bitwise-equal): flip the node back to
                    # resident on the sequence's page and drop the
                    # host copy — a free re-adoption
                    self.pool.forget_spilled(node["host"])
                    node["host"] = None
                    node["page"] = int(block_row[i])
                    self._by_page[int(block_row[i])] = key
                    self._spilled_nodes -= 1
                    self.pool.ref_page(int(block_row[i]))
                continue
            parent = key[0] if key[0] in self._nodes else None
            self._nodes[key] = {"page": int(block_row[i]), "parent": parent,
                                "children": 0, "tick": self._tick,
                                "pins": 0, "host": None}
            self._by_page[int(block_row[i])] = key
            if parent is not None:
                self._nodes[parent]["children"] += 1
            self.pool.ref_page(int(block_row[i]))
            self._m.registered_pages.inc()

    def pin(self, pages) -> None:
        """Mark cached pages as adopted by an in-flight request: a pinned
        node is untouchable by ``evict`` until ``unpin``, independent of
        what the pool's reference counts happen to say. Call on
        adoption; ``unpin`` when the adopting request finishes."""
        for pid in pages:
            key = self._by_page.get(int(pid))
            if key is not None:
                node = self._nodes[key]
                node["pins"] += 1
                if node["pins"] == 1:
                    self._pinned_nodes += 1

    def unpin(self, pages) -> None:
        for pid in pages:
            key = self._by_page.get(int(pid))
            if key is not None and self._nodes[key]["pins"] > 0:
                node = self._nodes[key]
                node["pins"] -= 1
                if node["pins"] == 0:
                    self._pinned_nodes -= 1

    def evict(self, n_pages: int) -> int:
        """Free up to ``n_pages`` device pages, REFUSING any node that
        is pinned by an in-flight request's block table (pin count from
        adoption) or whose page anyone besides the cache still
        references (rc > 1). With a host tier armed, cold nodes SPILL
        first (device page freed, KV retained host-side for later
        restore); whatever spilling cannot cover falls back to dropping
        LRU leaf nodes outright. Returns the number of pages actually
        returned to the free list — callers size retry loops on real
        capacity, so unrefs that free nothing don't count."""
        freed = self.spill(n_pages) if self.host_tier_pages > 0 else 0
        dropped = 0
        while freed + dropped < n_pages:
            leaves = [(node["tick"], key) for key, node in
                      self._nodes.items()
                      if node["children"] == 0 and node["pins"] == 0
                      and node["host"] is None
                      and self.pool._page_rc[node["page"]] == 1]
            if not leaves:
                break
            _, key = min(leaves, key=lambda t: t[0])
            if self._drop_node(key):
                dropped += 1
        if dropped:
            self._m.evicted_pages.inc(dropped)
        return freed + dropped

    def _drop_node(self, key: tuple) -> bool:
        """Remove one trie node entirely. Returns True when a DEVICE
        page actually returned to the free list (a spilled node's drop
        frees host RAM, not device pages)."""
        node = self._nodes.pop(key)
        if node["parent"] is not None:
            self._nodes[node["parent"]]["children"] -= 1
        if node["host"] is not None:
            self.pool.forget_spilled(node["host"])
            self._spilled_nodes -= 1
            return False
        self._by_page.pop(node["page"], None)
        return self.pool.unref_page(node["page"])

    def spill(self, n_pages: int) -> int:
        """Move up to ``n_pages`` cold resident nodes to the host tier,
        freeing their device pages. Candidates are exactly what the
        r13 ledger calls cache-only pages: unpinned, rc == 1 (a shared
        or adopted page never spills under a live reader). LRU order;
        under high free-list fragmentation the policy prefers, among
        the colder half, pages adjacent to the current free list so
        each spill extends a contiguous run. Past the host budget the
        coldest spilled leaves drop entirely."""
        freed = 0
        # one sort per spill() call (the per-page state this loop
        # mutates never re-ranks the survivors; re-sorting per page
        # made a blocked admission quadratic in the spill batch)
        cands = sorted(
            ((node["tick"], key) for key, node in self._nodes.items()
             if node["host"] is None and node["pins"] == 0
             and self.pool._page_rc[node["page"]] == 1),
            key=lambda t: t[0])     # trie keys are not comparable
        frag = (len(cands) > 1
                and self.pool.free_list_fragmentation() > 0.5)
        free = set(self.pool._free) if frag else None
        while freed < n_pages and cands:
            # the host tier is a HARD budget (operators size it
            # against real host RAM): make room by dropping the
            # coldest spilled leaves BEFORE spilling in, and stop
            # spilling entirely when nothing is droppable (all
            # spilled nodes interior with live children)
            if self._spilled_nodes >= self.host_tier_pages:
                self._drop_spilled_until(self.host_tier_pages - 1)
                if self._spilled_nodes >= self.host_tier_pages:
                    break
            idx = 0
            if frag:
                # fragmentation-aware tie-break: among the colder half,
                # spill a page that extends an existing free run
                for j in range(max(1, len(cands) // 2)):
                    pid = self._nodes[cands[j][1]]["page"]
                    if pid + 1 in free or pid - 1 in free:
                        idx = j
                        break
            _, key = cands.pop(idx)
            node = self._nodes[key]
            pid = node["page"]
            # fault check BEFORE mutation: an injected spill failure
            # leaves the node resident and propagates into replay
            self._f_spill.check(op="spill", page=pid)
            node["host"] = self.pool.spill_page(pid)
            node["page"] = None
            self._by_page.pop(pid, None)
            self._spilled_nodes += 1
            if self.pool.unref_page(pid):
                freed += 1
                if free is not None:
                    free.add(pid)
            self._m.spilled_pages.inc()
        return freed

    def _drop_spilled_until(self, limit: int) -> None:
        """Drop the coldest spilled LEAF nodes until the host tier
        holds at most ``limit`` pages (an interior spilled node waits
        for its children — dropping it would orphan their chains).
        ``spill`` calls this before every page it moves in, so the
        spilled census never exceeds ``host_tier_pages``."""
        while self._spilled_nodes > max(0, limit):
            spilled_leaves = [(node["tick"], key) for key, node in
                              self._nodes.items()
                              if node["host"] is not None
                              and node["children"] == 0
                              and node["pins"] == 0]
            if not spilled_leaves:
                break
            _, key = min(spilled_leaves, key=lambda t: t[0])
            self._drop_node(key)
            self._m.dropped_spilled.inc()

    def spilled_page_count(self) -> int:
        """Pages currently resident only in the host tier (O(1))."""
        return self._spilled_nodes

    def evictable_page_count(self) -> int:
        """Device pages ``evict``/``spill`` could free right now —
        resident, unpinned, cache-only (rc == 1). The preemption
        trigger consults this so a tight-deadline arrival never
        preempts a victim while plain eviction could still pay its
        page bill. With a host tier armed, any such node spills
        regardless of trie position; without one, ``evict`` drops
        LEAVES only, so a pinned/shared/spilled descendant blocks
        every ancestor from the cascade — counting those would make
        the preemption trigger skip a victim for pages eviction can
        never actually free."""
        free_ok = (lambda node: node["host"] is None
                   and node["pins"] == 0
                   and self.pool._page_rc[node["page"]] == 1)
        blocked: set = set()
        for node in self._nodes.values():
            if free_ok(node):
                continue
            k = node["parent"]
            while k is not None and k not in blocked:
                blocked.add(k)
                parent = self._nodes.get(k)
                k = parent["parent"] if parent is not None else None
        droppable = sum(1 for key, node in self._nodes.items()
                        if key not in blocked and free_ok(node))
        if self.host_tier_pages <= 0:
            return droppable
        # tier armed: nodes beyond the leaf-drop cascade free via
        # SPILL, but only as far as the HARD tier budget has room —
        # current headroom plus droppable spilled leaves (each drop
        # opens one slot; no cascade credit, so this under- rather
        # than over-estimates and the preemption trigger errs toward
        # protecting the deadline)
        flat = sum(1 for node in self._nodes.values() if free_ok(node))
        room = max(0, self.host_tier_pages - self._spilled_nodes)
        room += sum(1 for node in self._nodes.values()
                    if node["host"] is not None
                    and node["children"] == 0 and node["pins"] == 0)
        return droppable + min(room, max(0, flat - droppable))

    def pinned_page_count(self) -> int:
        """Pages untouchable by ``evict`` because an in-flight request's
        block table still points at them — the pinned-page pressure a
        shortfalling evict() reports instead of silently under-freeing.
        O(1): maintained on pin/unpin transitions (evict only ever drops
        pins==0 nodes), so the per-step gauge refresh costs nothing."""
        return self._pinned_nodes

    def peek(self, prompt: np.ndarray,
             include_spilled: bool = False) -> int:
        """Length (tokens) of the cached page-aligned prefix WITHOUT
        touching LRU ticks or hit/miss telemetry — the scheduler's
        prefix-aware admission probe (``lookup`` is the real,
        stats-bearing read at admission time). By default the probe
        counts DEVICE-resident pages only, so admission pricing stays
        honest (restoring a spilled page consumes a free page, exactly
        like fresh allocation); the fleet router's affinity probe passes
        ``include_spilled=True`` because a host-tier hit still beats
        re-running prefill on a cold replica."""
        n = 0
        for key in self._chunks(prompt):
            node = self._nodes.get(key)
            if node is None:
                break
            if node["host"] is not None and not include_spilled:
                break
            n += self.page_size
        return n


class ServingEngine:
    """Drive ``model`` (a GenerationMixin Layer) as a continuous-batching
    server. ``submit`` enqueues (deadline-slack-ordered, prefix-cache-
    aware admission); each ``step`` admits waiting requests, runs at
    most ONE prefill chunk (long prompts interleave with decode instead
    of stalling it), migrates the decode batch between bucket-ladder
    rungs as occupancy changes, and decodes one token for every active
    slot. ``run`` steps until drained and returns {rid: tokens}; the
    non-blocking surface is ``run_step``/``poll`` plus per-token
    ``submit(on_token=...)`` streaming callbacks.

    With ``draft_model=`` the engine decodes SPECULATIVELY (r16): the
    draft proposes γ tokens in one scanned dispatch, the target checks
    all of them (plus the bonus position) in one (1, γ+1) chunk through
    the r12 chunked-prefill machinery, and the KV cursors of both pools
    roll to exactly the accepted length. Greedy output is bit-identical
    to the non-speculative engine by construction; ``submit`` requests
    with ``temperature > 0`` sample losslessly through the rejection
    test. γ adapts per request from the observed accept rate, and a
    speculating request bills γ+1 decode slots against the
    FLAGS_serving_spec_max_slots budget, so rising batch occupancy caps
    γ down and finally prices speculation out in favor of the plain
    batched decode step."""

    def __init__(self, model, max_batch: int = 4, page_size: int = 64,
                 num_pages: Optional[int] = None, max_seq_len: int = 1024,
                 prefix_cache: bool = False,
                 bucket_ladder: Optional[Tuple[int, ...]] = None,
                 prefill_chunk: Optional[int] = None,
                 replica: str = "0",
                 host_tier_pages: Optional[int] = None,
                 draft_model=None,
                 kv_dtype: Optional[str] = None,
                 weight_dtype: Optional[str] = None,
                 tp_degree: Optional[int] = None):
        from .. import flags as _flags
        from ..jit import ensure_live

        self.model = model
        # identity of this engine in a multi-engine (fleet) process:
        # threaded as the `replica` label through every metric family,
        # so per-replica series never collide
        self.replica = str(replica)
        self.max_batch = max_batch
        self.max_seq_len = max_seq_len
        spec = model.cache_spec()
        if num_pages is None:
            # the pool budget decouples from the ladder's top rung:
            # FLAGS_serving_page_budget caps memory and lets admission
            # control absorb the difference; 0 keeps the worst-case
            # formula
            budget = int(_flags.get_flag("serving_page_budget"))
            # +1 pays for the reserved null page in BOTH modes, so a
            # budget of N means N USABLE pages (the formula's explicit
            # +1 already did)
            num_pages = (budget + 1 if budget > 0 else
                         1 + max_batch * (-(-max_seq_len // page_size)))
        # ---- chunked prefill: prompts longer than ``chunk`` prefill in
        # fixed-size chunks interleaved with decode steps (0 = off)
        self.chunk = int(_flags.get_flag("serving_prefill_chunk")
                         if prefill_chunk is None else prefill_chunk)
        if self.chunk < 0:
            raise ValueError(f"prefill_chunk must be >= 0, got {self.chunk}")
        # ---- batch-bucket ladder: decode runs at the smallest rung
        # covering demand; rungs above max_batch drop, max_batch is
        # always the top rung (so max_batch=4 == the fixed pre-r12 shape)
        if bucket_ladder is None:
            raw = str(_flags.get_flag("serving_bucket_ladder"))
            rungs = [int(r) for r in raw.replace(";", ",").split(",")
                     if r.strip()]
        else:
            rungs = [int(r) for r in bucket_ladder]
        if any(r < 1 for r in rungs):
            raise ValueError(f"bucket ladder rungs must be >= 1: {rungs}")
        self.ladder: Tuple[int, ...] = tuple(sorted(
            {r for r in rungs if r <= max_batch} | {max_batch}))
        self.bucket = self.ladder[0]
        self.bucket_patience = int(
            _flags.get_flag("serving_bucket_patience"))
        self._shrink_wait = 0
        # prefill-unit fairness flip-flop (chunks' turn when True)
        self._chunk_turn = False
        # host-side probes (test/bench surface, telemetry-independent)
        self.bucket_migrations = 0
        self.chunk_dispatches = 0
        self.max_decode_stall = 0.0
        params, buffers = model.raw_state()
        ensure_live(params, "call step.sync_to_model() first.")
        self._params, self._buffers = params, buffers
        dtype = jnp.result_type(next(iter(params.values())))
        # ---- quantized serving (r18): KV pool storage dtype and the
        # fused N-layer stacked-weight dtype are engine identity — both
        # reach compiled programs only through DecodeKey.extra
        self.kv_dtype = str(_flags.get_flag("serving_kv_dtype")
                            if kv_dtype is None else kv_dtype)
        if self.kv_dtype not in ("native", "int8"):
            raise ValueError(
                f"kv_dtype must be 'native' or 'int8', got {self.kv_dtype!r}")
        self.weight_dtype = str(_flags.get_flag("fused_weight_dtype")
                                if weight_dtype is None else weight_dtype)
        if self.weight_dtype not in ("native", "int4"):
            raise ValueError(f"weight_dtype must be 'native' or 'int4', "
                             f"got {self.weight_dtype!r}")
        # ---- tensor-parallel decode (r19): shard the stacked fused
        # weights column/row-wise and the paged KV pool over kv-heads
        # across the mp axis. Engine identity like the dtypes above —
        # it reaches compiled programs only through DecodeKey.extra
        self.tp_degree = int(_flags.get_flag("serving_tp_degree")
                             if tp_degree is None else tp_degree)
        if self.tp_degree < 1:
            raise ValueError(
                f"tp_degree must be >= 1, got {self.tp_degree}")
        self._tp_mesh = None
        self._tp_axis = "mp"
        self._pool_sharding = None
        if self.tp_degree > 1:
            if self.weight_dtype == "int4":
                raise ValueError(
                    "tp_degree > 1 with weight_dtype='int4' is not "
                    "supported: Int4Tiles nibble packing does not commute "
                    "with the head-shard permutation (pack after sharding "
                    "is a chip-window follow-up)")
            if spec[0][0] % self.tp_degree:
                raise ValueError(
                    f"tp_degree={self.tp_degree} must divide the model's "
                    f"kv-head count ({spec[0][0]}) so the paged pool "
                    "partitions evenly over kv-heads")
            from jax.sharding import Mesh as _Mesh
            from jax.sharding import NamedSharding as _NS
            from jax.sharding import PartitionSpec as _P
            from ..distributed.communication.group import resolve_group_axis
            from ..distributed.fleet.base_topology import (
                try_get_hybrid_communicate_group,
            )
            # the mp process group (when fleet.init built one) names the
            # axis and the member devices; a bare runtime falls back to
            # the first tp devices under the canonical "mp" axis name
            hcg = try_get_hybrid_communicate_group()
            group = None
            if (hcg is not None and
                    hcg.get_model_parallel_world_size() == self.tp_degree):
                group = hcg.get_model_parallel_group()
            self._tp_axis = resolve_group_axis(group, "mp")
            devs = jax.devices()
            if group is not None:
                members = [devs[r % len(devs)] for r in group.ranks]
            elif len(devs) >= self.tp_degree:
                members = devs[:self.tp_degree]
            else:
                raise ValueError(
                    f"tp_degree={self.tp_degree} needs that many devices; "
                    f"the runtime has {len(devs)}")
            self._tp_mesh = _Mesh(np.array(members), (self._tp_axis,))
            # canonical partition of every per-layer pool leaf: kv-heads
            # lead on the payload AND the int8 scale band, so one spec
            # shards both together
            self._pool_sharding = _NS(self._tp_mesh,
                                      _P(self._tp_axis, None, None, None))
        # pool geometry is kept so replay recovery can allocate FRESH
        # pools with the identical shape (same compiled programs apply)
        self._pool_geom = dict(
            num_layers=len(spec), num_pages=num_pages, page_size=page_size,
            num_kv_heads=spec[0][0], head_dim=spec[0][1],
            max_batch=max_batch, max_seq_len=max_seq_len, dtype=dtype,
            reserve_null_page=True, kv_dtype=self.kv_dtype)
        self.pool = PagedKVCache(**self._pool_geom)
        self._shard_pool(self.pool)
        maxpos = getattr(getattr(model, "config", None),
                         "max_position_embeddings", None)
        if maxpos is not None and max_seq_len > maxpos:
            raise ValueError(
                f"engine max_seq_len ({max_seq_len}) exceeds the model's "
                f"max_position_embeddings ({maxpos})")
        # ---- host-RAM KV tier (r14): prefix-cache eviction spills to
        # host RAM up to this many pages instead of dropping (0 = off)
        self.host_tier_pages = int(
            _flags.get_flag("serving_kv_host_tier_pages")
            if host_tier_pages is None else host_tier_pages)
        # ---- SLO-aware preemption (r14): a tight-deadline arrival may
        # unseat the slackest running request (bounded per victim),
        # which replays later from host state bit-identically
        self.preempt_enabled = bool(_flags.get_flag("serving_preempt"))
        self.preempt_budget = int(_flags.get_flag("serving_preempt_budget"))
        self.preempt_margin = float(
            _flags.get_flag("serving_preempt_margin"))
        self.preempt_horizon = float(
            _flags.get_flag("serving_preempt_horizon"))
        self.preemptions = 0        # host probe (telemetry-independent)
        self._host_tier_peak = 0
        # ---- speculative decoding (r16): a draft model turns decode
        # into propose-γ/verify-once rounds. The draft keeps its OWN
        # paged pool in slot lockstep with the target's; the draft
        # pool's seq_lens row IS the draft-KV cursor, so falling behind
        # (admission prefilled the target only, or plain decode ran
        # while speculation was priced out) is detected by comparing
        # the two cursors — no separate bookkeeping to drift
        self.draft_model = draft_model
        self._draft_pool: Optional[PagedKVCache] = None
        if draft_model is not None:
            dspec = draft_model.cache_spec()
            dparams, dbuffers = draft_model.raw_state()
            ensure_live(dparams, "call step.sync_to_model() first.")
            self._draft_params, self._draft_buffers = dparams, dbuffers
            dmax = getattr(getattr(draft_model, "config", None),
                           "max_position_embeddings", None)
            if dmax is not None and max_seq_len > dmax:
                raise ValueError(
                    f"engine max_seq_len ({max_seq_len}) exceeds the "
                    f"draft model's max_position_embeddings ({dmax})")
            # ALWAYS worst-case pages (the serving_page_budget cap does
            # not apply): the target pool admits against its budget —
            # possibly on adopted shared-prefix pages — and the draft
            # sync must then never fail an allocate of the same span.
            # Draft KV is a fraction of target KV, so the safety margin
            # is cheap where it matters
            self._draft_geom = dict(
                num_layers=len(dspec),
                num_pages=1 + max_batch * (-(-max_seq_len // page_size)),
                page_size=page_size,
                num_kv_heads=dspec[0][0], head_dim=dspec[0][1],
                max_batch=max_batch, max_seq_len=max_seq_len,
                dtype=jnp.result_type(next(iter(dparams.values()))),
                reserve_null_page=True, kv_dtype=self.kv_dtype)
            self._draft_pool = PagedKVCache(**self._draft_geom)
            self._shard_pool(self._draft_pool)
            raw = str(_flags.get_flag("serving_spec_rungs"))
            srungs = sorted({int(r) for r in raw.replace(";", ",").split(",")
                             if r.strip()})
            if not srungs or srungs[0] < 1:
                raise ValueError(
                    f"serving_spec_rungs must name rungs >= 1: {raw!r}")
            self.spec_rungs: Tuple[int, ...] = tuple(srungs)
            g0 = int(_flags.get_flag("serving_spec_gamma"))
            self.spec_gamma_default = max(
                r for r in self.spec_rungs if r <= max(g0, srungs[0]))
            self.spec_adaptive = bool(
                _flags.get_flag("serving_spec_adaptive"))
            # slot budget for γ+1 pricing; the floor keeps a lone
            # decode row affordable at the smallest rung even on tiny
            # engines (batch-1 speculation is the headline win)
            self.spec_slots = (int(_flags.get_flag("serving_spec_max_slots"))
                               or max(max_batch, srungs[0] + 1))
            self.spec_sync_chunk = max(
                1, int(_flags.get_flag("serving_spec_sync_chunk")))
            self._f_spec_draft = faults.site("spec_draft")
            self._f_spec_verify = faults.site("spec_verify")
            self._spec_fns: Dict[tuple, object] = {}
            self._spec_keys: Dict[tuple, object] = {}
            self.spec_draft_key = None      # test probes: last-used keys
            self.spec_verify_key = None
            # host probes (bench/test surface, telemetry-independent)
            self.spec_rounds = 0
            self.spec_tokens_accepted = 0
            self.spec_tokens_rejected = 0
            self.spec_last_gamma = 0
        self._slots: List[Optional[Request]] = [None] * max_batch
        self._queue: List[Request] = []
        self._results: Dict[int, List[int]] = {}
        self._status: Dict[int, str] = {}
        self._last_tok = np.zeros((max_batch,), np.int32)
        self._next_rid = 0
        self._prefill_fn = None
        self._chunk_fn = None
        self._decode_fns: Dict[int, object] = {}    # bucket rung -> fn
        self._decode_keys: Dict[int, object] = {}
        self.decode_key = None      # key of the current rung (test probe)
        # FLAGS_fused_block_layers > 1: per-group MultiBlockDecodeWeights
        # (q|k|v and gate|up merged into stacked wider matmuls), built
        # ONCE on first N-layer program build and passed to every decode
        # step as traced args. One extra HBM copy of the layer weights —
        # the originals still serve prefill/chunk/spec programs. None
        # whenever the N-layer path doesn't apply (N=1, generic model,
        # int8 fallback), which is also the dispatch-site discriminant.
        self._stacked: Optional[tuple] = None
        # streaming: (callback, rid, token|None, done) events buffered
        # during a step and drained AFTER dispatch/recovery, so a user
        # callback that raises never masquerades as a dispatch failure
        self._events: List[tuple] = []
        # streaming-callback registry: rid -> on_token. Engine-LOCAL by
        # design — callbacks never ride the Request objects the export/
        # harvest seams detach (a bound callable cannot serialize across
        # a process boundary); take_callbacks() strips the registry at
        # export and inject_request/adopt_request re-bind on the far side
        self._callbacks: Dict[int, Callable] = {}
        self._prefix_enabled = bool(prefix_cache)
        self._prefix = (PrefixCache(self.pool, replica=self.replica,
                                    host_tier_pages=self.host_tier_pages)
                        if prefix_cache else None)
        # ---- fault tolerance: injection sites bind at construction
        # (NULL stubs when FLAGS_fault_inject is unset — zero hot-path
        # cost, the telemetry idiom) and the replay-recovery budget
        self._f_prefill = faults.site("prefill")
        self._f_chunk = faults.site("chunk_prefill")
        self._f_decode = faults.site("decode_dispatch")
        self._f_migrate = faults.site("bucket_migrate")
        self._f_preempt = faults.site("preempt")
        self.max_retries = int(_flags.get_flag("serving_max_retries"))
        self.retry_backoff = float(
            _flags.get_flag("serving_retry_backoff"))
        self._consec_failures = 0   # engine-wide no-progress failures
        self._failed_admission: Optional[Request] = None
        self._head_blocked = False  # last _next_admission left the
        # slack head page-blocked (bypass admits must not clear gauges)
        # per-step memo of _shared_adopt_pages by rid: the scheduler
        # probes the same requests several times per step (migration
        # demand, head bill per free slot, bypass scan, unit routing)
        # and each probe re-walks the prefix trie over the full prompt
        self._probe_memo: Dict[int, int] = {}
        # flag resolution happens ONCE per engine; the PROGRAM_FLAGS
        # snapshot (every flag a traced program can read — kernel
        # dispatch, flash blocks, compact stats, matmul precision) is
        # part of the program-cache key, so engines built under
        # different flag settings compile and cache distinct steps
        # instead of silently serving a program compiled under stale
        # flags, while eager-only flags (log_level, benchmark) never
        # force a spurious recompile
        from .program_cache import model_signature
        self._flags = _flags.snapshot(_flags.PROGRAM_FLAGS)
        self._model_sig = model_signature(model)
        self._draft_sig = (model_signature(draft_model)
                           if draft_model is not None else None)
        # telemetry binding is per-engine and resolved once here (the
        # no-op stubs cost one method call per write when disabled);
        # the replica id labels every series so fleet engines coexist
        self._m = (_EngineTelemetry(self.replica, str(self.tp_degree))
                   if obs.enabled()
                   else _NullEngineTelemetry(self.replica,
                                             str(self.tp_degree)))
        # pool-ledger fragmentation memo: recompute only when the pool's
        # free-list epoch moved (steady-state decode never moves it)
        self._pool_frag_epoch = -1
        self._pool_frag = 0.0
        self._observe_bucket()

    # ------------------------------------------------------------ frontend
    def submit(self, prompt, max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None,
               deadline: Optional[float] = None,
               on_token: Optional[Callable] = None,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 1.0, seed: Optional[int] = None) -> int:
        """Enqueue one request. ``deadline`` (seconds from now) bounds
        its total latency: a request past its deadline — queued or in
        flight — is terminated ``TIMEOUT`` at the next step boundary
        with whatever tokens it produced. The scheduler admits by
        deadline SLACK (tightest first; no-deadline requests keep FIFO
        order among themselves). ``on_token(rid, token, done)`` streams
        tokens as they are generated: one call per token with
        ``done=False``, then one final ``(rid, None, True)`` when the
        request reaches a terminal status — callbacks fire on the
        caller's thread at step boundaries, after dispatch/recovery, so
        a raising callback surfaces to the caller instead of tripping
        replay recovery.

        ``temperature``/``top_k``/``top_p`` select the sampling law
        (0 = greedy, the default). Sampling requires a speculative
        engine (``draft_model=``): the verify program's rejection
        sampler is the only sampler — it draws the exact
        temperature/top-k/top-p-filtered target distribution. ``seed``
        keys the request's sampling stream (default: its rid), and the
        stream is position-keyed, so replay recovery and preemption
        reproduce sampled continuations bit-identically."""
        if temperature is not None and float(temperature) < 0.0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        if float(temperature or 0.0) > 0.0 and self.draft_model is None:
            raise ValueError(
                "temperature > 0 requires a speculative engine "
                "(ServingEngine(..., draft_model=...)): the spec verify "
                "program is the engine's sampler")
        prompt = np.asarray(
            prompt._value if hasattr(prompt, "_value") else prompt,
            np.int32).reshape(-1)
        if len(prompt) + max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds engine max_seq_len "
                f"({self.max_seq_len})")
        # a request that can never fit would deadlock FIFO admission
        need = -(-(len(prompt) + max_new_tokens) // self.pool.page_size)
        usable = self.pool.num_pages - 1        # null page reserved
        if need > min(usable, self.pool.max_pages_per_seq):
            raise ValueError(
                f"request needs {need} pages but the pool can ever offer "
                f"{min(usable, self.pool.max_pages_per_seq)}")
        rid = self._next_rid
        self._next_rid += 1
        req = Request(rid, prompt, int(max_new_tokens), eos_token_id)
        if on_token is not None:
            self._callbacks[rid] = on_token
        req.temperature = float(temperature or 0.0)
        req.top_k = int(top_k)
        req.top_p = float(top_p)
        req.seed = int(seed) if seed is not None else rid
        req.t_submit = time.perf_counter()
        if deadline is not None:
            req.deadline = req.t_submit + float(deadline)
        self._queue.append(req)
        self._m.submitted.inc()
        return rid

    def has_work(self) -> bool:
        return bool(self._queue) or any(s is not None for s in self._slots)

    def load(self) -> Tuple[int, int]:
        """``(deadline_bearing, total)`` live request counts (queued +
        in flight) — the fleet router's deadline-aware load-balance
        probe. Cheap: two list scans over host bookkeeping."""
        live = [r for r in self._slots if r is not None] + self._queue
        return (sum(1 for r in live if r.deadline is not None), len(live))

    def run_step(self) -> bool:
        """The non-blocking pump: one scheduler round (admission, at
        most one prefill chunk, one decode dispatch), then returns
        whether work remains — callers interleave ``run_step`` with
        ``poll``/``results`` to drain tokens while the engine runs,
        instead of blocking in :meth:`run`."""
        self.step()
        return self.has_work()

    def poll(self, rid: int) -> Dict[str, object]:
        """Non-blocking progress probe for one request: ``{"status",
        "tokens", "done"}`` with the tokens emitted SO FAR (a snapshot —
        safe to mutate). Completed requests report their terminal
        status until :meth:`run`'s next drain prunes them."""
        if rid in self._results:
            return {"status": self._status.get(rid, OK),
                    "tokens": list(self._results[rid]), "done": True}
        for req in list(self._slots) + self._queue:
            if req is not None and req.rid == rid:
                return {"status": "PENDING", "tokens": list(req.tokens),
                        "done": False}
        raise KeyError(f"unknown or already-drained request id {rid}")

    def run(self, max_wall: Optional[float] = None) -> Dict[int, List[int]]:
        """Step until drained and return ``{rid: tokens}`` (partial
        tokens for FAILED/TIMEOUT requests — check :meth:`status`).
        ``max_wall`` is the watchdog: past it, everything still queued
        or in flight is terminated ``TIMEOUT`` and ``run`` returns
        instead of spinning on a wedged backend."""
        t0 = time.perf_counter()
        while self.has_work():
            if max_wall is not None and \
                    time.perf_counter() - t0 > max_wall:
                self._expire_all("run(max_wall=%.3f) watchdog" % max_wall)
                self._drain_events()
                break
            self.step()
        out, self._results = self._results, {}
        # statuses are retained for exactly the requests this drain
        # returned: a long-lived engine must not accumulate one status
        # entry per request forever
        self._status = {rid: self._status[rid] for rid in out
                        if rid in self._status}
        return out

    def results(self) -> Dict[int, List[int]]:
        """Completed results accumulated so far, WITHOUT draining them —
        the exception-safety accessor: after a mid-``run`` raise, every
        request that finished before the failure is retrievable here
        (``run`` only hands over-and-clears on a clean drain)."""
        return {rid: list(toks) for rid, toks in self._results.items()}

    def take_results(self) -> Dict[int, List[int]]:
        """Drain completed results (and their statuses): the
        ``run_step()`` loop's collection surface. A long-lived server
        pumping ``run_step`` must drain through here (or through
        ``run``) — ``results()``/``poll()`` deliberately never free the
        per-request entries, so without a drain they grow one entry per
        completed request forever. Check :meth:`status`/:meth:`statuses`
        BEFORE draining; drained rids poll as unknown afterwards."""
        out, self._results = self._results, {}
        for rid in out:
            self._status.pop(rid, None)
        return out

    def status(self, rid: int) -> str:
        """Terminal status for ``rid``: ``OK`` / ``FAILED`` / ``TIMEOUT``
        (``PENDING`` while queued or in flight). Statuses survive until
        the NEXT completed ``run`` drain, then prune with its results."""
        return self._status.get(rid, "PENDING")

    def statuses(self) -> Dict[int, str]:
        return dict(self._status)

    # ---------------------------------------------- fleet router surface
    def export_requests(self) -> List[Request]:
        """Detach every live request — in flight and queued — as pure
        host state, in submission order: the fleet router's
        replica-loss harvest. In-flight requests reset to replay form
        (prompt + emitted tokens; pins, slots, cursors dropped), so
        re-routing them through another replica's admission produces
        the bit-identical greedy continuation. The engine is left with
        no pending work; completed results stay until drained. Pages
        release when the pool is still alive (a lost replica's pool may
        be detached — its device state is gone either way). Streaming
        callbacks do NOT ride the exported requests (host bundles stay
        transportable): grab them with :meth:`take_callbacks` and
        re-bind each via ``inject_request(req, on_token=...)``."""
        live = [r for r in self._slots if r is not None]
        pool_alive = self.pool.k_pages and self.pool.k_pages[0] is not None
        out = sorted(live + self._queue, key=lambda r: r.rid)
        for req in live:
            if pool_alive and req.slot is not None:
                self.pool.free_sequence(req.slot)
        for req in out:
            self._to_replay_form(req)
        self._slots = [None] * self.max_batch
        self._queue = []
        self._last_tok[:] = 0
        return out

    def take_callbacks(self) -> Dict[int, Callable]:
        """Detach the rid -> streaming-callback registry — the
        strip-at-export half of the callback discipline. Callbacks are
        engine-local and never ride the ``Request`` bundles the export/
        harvest seams detach (a bound callable cannot serialize across
        a process boundary); the caller re-binds each one on the far
        side via ``inject_request(..., on_token=)`` /
        ``adopt_request(..., on_token=)``."""
        out, self._callbacks = self._callbacks, {}
        return out

    def inject_request(self, req: Request,
                       on_token: Optional[Callable] = None) -> int:
        """Enqueue an EXISTING request object under a fresh local rid —
        the fleet router's re-route half of :meth:`export_requests`.
        Prompt, emitted tokens, deadline and budgets ride along, so
        admission treats a token-bearing injection exactly like a
        replay (prefill from prompt + tokens, bit-identical greedy
        continuation). ``on_token`` re-binds the request's streaming
        callback under its fresh rid (the re-bind-on-adopt half of
        :meth:`take_callbacks`)."""
        req.rid = self._next_rid
        self._next_rid += 1
        req.status = "PENDING"
        req.error = None
        if on_token is not None:
            self._callbacks[req.rid] = on_token
        self._queue.append(req)
        # NOT counted as a submission: the request was submitted once,
        # on its original replica — fleet_rerouted_requests is the
        # re-route count, and double-counting here would inflate every
        # fleet-wide sum over serving_requests_submitted{replica}
        return req.rid

    # ----------------------------------- disaggregated handoff (r19)
    def harvest_request(self, rid: int) -> dict:
        """Detach ONE live greedy request WITH its written KV pages —
        the prefill-replica half of prefill→decode disaggregation. The
        pages spill verbatim (int8 payload + scale band included) and
        leave with the request, so the decode replica resumes WITHOUT
        re-running prefill and the greedy continuation stays
        bit-identical: the pool bits move, nothing is recomputed.
        Returns the bundle :meth:`adopt_request` seats — pure host
        state (``HANDOFF_SCHEMA_VERSION``-tagged, pickle-transportable;
        the streaming callback is stripped, re-bind it via
        ``adopt_request(..., on_token=)``); transfer it however the
        deployment likes (the dryrun harness rides the deterministic
        p2p mailbox)."""
        req = next((r for r in self._slots
                    if r is not None and r.rid == rid), None)
        if req is None or req.slot is None:
            raise ValueError(
                f"harvest_request: rid {rid} is not seated in a slot "
                "(queued/completed requests re-route through "
                "export_requests/inject_request instead)")
        if req.prefill_pos is not None or req.pending:
            raise ValueError(
                "harvest_request: request is mid-prefill (chunk cursor "
                "or teacher-forced suffix pending) — hand off after its "
                "first generated token")
        if req.temperature > 0.0:
            raise ValueError(
                "harvest_request: sampled requests park their KV cursor "
                "in the spec verify program; only greedy requests hand "
                "off with pages")
        if not self.pool.k_pages or self.pool.k_pages[0] is None:
            raise RuntimeError("harvest_request: pool is detached")
        slot = req.slot
        seq_len = int(self.pool.seq_lens[slot])
        last_tok = int(self._last_tok[slot])
        n_pages = int(self.pool._pages_used[slot])
        pages = []
        for i in range(n_pages):
            hp = self.pool.spill_page(int(self.pool.block_tables[slot, i]))
            # the copy leaves with the request — it was never this
            # pool's host-tier resident, so retire it from the census
            self.pool.forget_spilled(hp)
            pages.append(hp)
        self.pool.free_sequence(slot)
        self._to_replay_form(req)
        self._slots[slot] = None
        self._last_tok[slot] = 0
        # strip-at-export: the callback is engine-local state, never
        # part of the transportable bundle (the adopter re-binds one)
        self._callbacks.pop(rid, None)
        return {"v": HANDOFF_SCHEMA_VERSION, "request": req,
                "pages": pages, "seq_len": seq_len,
                "last_token": last_tok}

    def adopt_request(self, bundle: dict,
                      on_token: Optional[Callable] = None) -> int:
        """Seat a harvested request mid-stream — the decode-replica
        half of :meth:`harvest_request`: allocate the span, write the
        transferred pages into the fresh block table
        (:meth:`PagedKVCache.adopt_page`), restore the KV cursor and
        the last emitted token, and resume decoding under a fresh local
        rid. Pool geometry must match byte-for-byte (same page layout =
        same compiled programs serve the adopted row). ``on_token``
        re-binds a streaming callback under the fresh rid (callbacks
        never ride the bundle — the re-bind-on-adopt half of the
        callback discipline)."""
        v = bundle.get("v")
        if v != HANDOFF_SCHEMA_VERSION:
            raise ValueError(
                f"adopt_request: bundle schema version {v!r} != this "
                f"engine's {HANDOFF_SCHEMA_VERSION} — the disaggregated "
                "pair must run the same handoff revision (re-harvest on "
                "a matching build instead of mis-seating pages)")
        req: Request = bundle["request"]
        pages = bundle["pages"]
        if not self.pool.k_pages or self.pool.k_pages[0] is None:
            raise RuntimeError("adopt_request: pool is detached")
        if pages and pages[0].nbytes != self.pool.bytes_per_page:
            raise ValueError(
                f"adopt_request: page layout mismatch — bundle pages "
                f"are {pages[0].nbytes} bytes, this pool's are "
                f"{self.pool.bytes_per_page} (layers/kv-heads/page_size/"
                "kv_dtype must agree across the disaggregated pair)")
        try:
            slot = self._slots.index(None)
        except ValueError:
            raise RuntimeError(
                "adopt_request: no free slot (drain or grow max_batch)")
        try:
            self.pool.allocate(slot,
                               len(req.prompt) + int(req.max_new_tokens))
        except RuntimeError:
            # partial allocation is recorded in _pages_used — return it
            self.pool.free_sequence(slot)
            raise
        if int(self.pool._pages_used[slot]) < len(pages):
            self.pool.free_sequence(slot)
            raise ValueError(
                f"adopt_request: bundle carries {len(pages)} pages but "
                f"the span only needs {int(self.pool._pages_used[slot])}")
        for i, hp in enumerate(pages):
            self.pool.adopt_page(hp, int(self.pool.block_tables[slot, i]))
        self.pool.seq_lens[slot] = int(bundle["seq_len"])
        req.rid = self._next_rid
        self._next_rid += 1
        req.slot = slot
        req.status = "PENDING"
        req.error = None
        now = time.perf_counter()
        req.t_submit = req.t_submit or now
        req.t_last = now
        if on_token is not None:
            self._callbacks[req.rid] = on_token
        self._slots[slot] = req
        self._last_tok[slot] = int(bundle["last_token"])
        return req.rid

    # ------------------------------------------------- compiled programs
    def _key(self, kind: str, bucket: Optional[int] = None,
             extra: Tuple = ()):
        from .program_cache import DecodeKey
        # the kv/weight storage dtypes are program identity (r18): a
        # dtype flip must never re-serve a stale cached program, so the
        # discriminant rides every key's extra (the pool dtype string
        # below also flips to "int8" for quantized pools, but the extra
        # covers the weight dtype and keys built before pools exist)
        extra = tuple(extra) + ((key_vocab.TAG_KV, self.kv_dtype),
                                (key_vocab.TAG_WT, self.weight_dtype))
        # tp rides the extra ONLY when armed, so every tp=1 key (and the
        # banked artifacts keyed on it) stays byte-identical to r18
        if self.tp_degree > 1:
            extra = extra + ((key_vocab.TAG_TP, self.tp_degree),)
        return DecodeKey(
            kind=kind, model_sig=self._model_sig,
            batch_bucket=self.max_batch if bucket is None else bucket,
            page_budget=(self.pool.num_pages, self.pool.page_size,
                         self.pool.max_pages_per_seq),
            dtype=str(self.pool.k_pages[0].dtype),
            flags=self._flags.as_tuple(), extra=extra)

    def _fused_spec(self, draft: bool = False):
        """The model's fused-block layout when the fused path applies:
        FLAGS_fused_block_decode on, the model publishes
        ``block_decode_spec()``, and every named weight is live in the
        param/buffer dicts (a weight-quantized model restructures its
        Linears into int8 buffers and falls back to the generic step).
        ``draft=True`` probes the speculative DRAFT model instead — the
        draft-propose scan fuses per-layer exactly like the batched
        decode step when its model qualifies (the draft always stays
        per-layer: its scan carries one layer's pools at a time, and
        γ-token proposal latency is not where N-layer fusion pays)."""
        if not self._flags.fused_block_decode:
            return None
        model = self.draft_model if draft else self.model
        get_spec = getattr(model, "block_decode_spec", None)
        if get_spec is None:
            return None
        n = int(self._flags.fused_block_layers)
        if n > 1 and not draft:
            try:
                spec = get_spec(fused_layers=n)
            except TypeError:
                # model predates the stacked layout (no fused_layers
                # kwarg): serve it per-layer rather than refuse
                spec = get_spec()
        else:
            spec = get_spec()
        if spec is None:
            return None
        allp = ({**self._draft_buffers, **self._draft_params} if draft
                else {**self._buffers, **self._params})
        names = [spec["embed"], spec["final_norm"]]
        if spec["lm_head"]:
            names.append(spec["lm_head"])
        for lw in spec["layers"]:
            names.extend(lw.values())
        if not all(allp.get(n) is not None for n in names):
            return None
        return spec

    def _prefill_program(self):
        if self._prefill_fn is None:
            from .program_cache import decode_program_cache
            self._prefill_fn = decode_program_cache().get(
                self._key("prefill"),
                functools.partial(_build_prefill, model=self.model))  # keycheck: disable=KEY002 — the documented model-object closure (model_sig rides the key)
        return self._prefill_fn

    def _chunk_program(self):
        """The chunked-prefill program: ONE cached compiled step per
        (chunk length, model/pool config) — every chunk of every prompt
        dispatches the same fixed (1, chunk) shape (the final partial
        chunk pads), so prompt length never retraces."""
        if self._chunk_fn is None:
            from .program_cache import decode_program_cache
            self._chunk_fn = decode_program_cache().get(
                self._key("prefill_chunk", bucket=1,
                          extra=(self.chunk,)),
                functools.partial(_build_chunk_prefill, model=self.model))  # keycheck: disable=KEY002 — the documented model-object closure (model_sig rides the key)
        return self._chunk_fn

    def _stacked_weights(self, spec) -> tuple:
        """Build (once) the per-group MultiBlockDecodeWeights the N-layer
        decode programs take as traced args: each group's
        BlockDecodeWeights stacked along a leading layer axis, q|k|v and
        gate|up concatenated into single wider matmul operands.

        Under tp > 1 the stacks are additionally permuted into the
        shard-major Megatron layout (``shard_block_weights``) and
        committed to the tp mesh with the canonical per-field shardings
        — column-parallel wqkv/wgu split their LAST axis, row-parallel
        wo/wd their middle (contraction) axis, norms replicate — so
        every decode dispatch reuses one stable placement and never
        retraces on a sharding flip."""
        if self._stacked is None:
            from ..kernels.fused_block_decode import (BlockDecodeWeights,
                                                      stack_block_weights)
            allp = {**self._buffers, **self._params}
            self._stacked = tuple(
                stack_block_weights([
                    BlockDecodeWeights(
                        **{f: allp[n]
                           for f, n in spec["layers"][i].items()})
                    for i in group], weight_dtype=self.weight_dtype)
                for group in spec["layer_groups"])
            if self.tp_degree > 1:
                from jax.sharding import NamedSharding as _NS
                from jax.sharding import PartitionSpec as _P
                from ..kernels.fused_block_decode import (
                    MultiBlockDecodeWeights, shard_block_weights)
                ax = self._tp_axis
                shardings = MultiBlockDecodeWeights(
                    ln1=_NS(self._tp_mesh, _P()),
                    wqkv=_NS(self._tp_mesh, _P(None, None, ax)),
                    wo=_NS(self._tp_mesh, _P(None, ax, None)),
                    ln2=_NS(self._tp_mesh, _P()),
                    wgu=_NS(self._tp_mesh, _P(None, None, ax)),
                    wd=_NS(self._tp_mesh, _P(None, ax, None)))
                self._stacked = tuple(
                    jax.device_put(
                        shard_block_weights(
                            g, self.tp_degree,
                            num_heads=spec["num_heads"],
                            num_kv_heads=spec["num_kv_heads"]),
                        shardings)
                    for g in self._stacked)
        return self._stacked

    def _decode_program(self, bucket: int):
        """The decode step for one bucket rung, compiled once per rung
        and cached — bucket migration swaps between already-compiled
        programs instead of retracing. With FLAGS_fused_block_layers=N
        and a model that publishes ``layer_groups``, the rung's program
        is the N-layer kernel step (DecodeKey.extra carries the
        layer-group shape so same-model engines under a different N
        never share a program)."""
        fn = self._decode_fns.get(bucket)
        if fn is None:
            from .program_cache import decode_program_cache
            spec = self._fused_spec()
            groups = spec.get("layer_groups") if spec else None
            if spec and self.tp_degree > 1:
                # tensor-parallel rung: every fused arm (N=1 included)
                # consumes stacked weights through ONE shard_map body —
                # a per-layer group chain IS the N=1 stacked layout
                if not groups:
                    spec = dict(spec)
                    groups = [[i] for i in range(len(spec["layers"]))]
                    spec["layer_groups"] = groups
                self._stacked_weights(spec)
                if any(len(g) > 1 for g in groups):
                    key = self._key(
                        "decode_fused_nlayer", bucket=bucket,
                        extra=(key_vocab.TAG_NLAYER,
                               tuple(len(g) for g in groups)))
                else:
                    # all-singleton groups ARE the N=1 stacked layout:
                    # model_sig pins the layer count, so a (1,)*L shape
                    # tag adds nothing — key it as plain decode_fused
                    # (the ("tp", N) pair still separates it from the
                    # single-device program) so the kind keeps ONE
                    # extra schema package-wide (KEY006)
                    key = self._key("decode_fused", bucket=bucket)
                builder = functools.partial(
                    _build_fused_nlayer_decode_tp, spec=spec,
                    snap=self._flags, mesh=self._tp_mesh,
                    axis=self._tp_axis, tp=self.tp_degree)
            elif groups:
                self._stacked_weights(spec)
                key = self._key(
                    "decode_fused_nlayer", bucket=bucket,
                    extra=(key_vocab.TAG_NLAYER,
                           tuple(len(g) for g in groups)))
                builder = functools.partial(_build_fused_nlayer_decode,
                                            spec=spec, snap=self._flags)
            elif spec:
                key = self._key("decode_fused", bucket=bucket)
                builder = functools.partial(_build_fused_decode, spec=spec,
                                            snap=self._flags)
            else:
                key = self._key("decode_generic", bucket=bucket)
                builder = functools.partial(_build_generic_decode,
                                            model=self.model)  # keycheck: disable=KEY002 — the documented model-object closure (model_sig rides the key)
            fn = decode_program_cache().get(key, builder)
            self._decode_fns[bucket] = fn
            self._decode_keys[bucket] = key
        self.decode_key = self._decode_keys.get(bucket, self.decode_key)
        return fn

    # ----------------------------------------------------------- internals
    # Donation discipline (tracecheck TRC003): the compiled programs
    # donate their pools argument, so the dispatch sites pass
    # ``self.pool.take_pools()`` — the cache's references are detached
    # BEFORE the buffers are invalidated by donation, and ``_store``
    # installs the step's returned pools.  A dispatch that raises leaves
    # the pool explicitly empty (take_pools refuses a second detach)
    # rather than silently aliasing deleted device buffers.

    def _shard_pool(self, pool) -> None:
        """Commit every per-layer pool leaf onto the canonical kv-head
        NamedSharding (the int8 payload and its per-token-row scale band
        both lead with the kv-head axis, so one spec shards both). A
        pool whose kv-head count does not divide tp stays replicated (a
        narrow draft model); no-op at tp=1 or on a detached pool. All
        host bookkeeping — ledger, spill/restore, replay recovery — is
        kv-head-count-invariant, so it needs no per-shard twin."""
        if (self._pool_sharding is None or pool is None
                or not pool.k_pages or pool.k_pages[0] is None
                or pool.num_kv_heads % self.tp_degree):
            return
        for i in range(len(pool.k_pages)):
            pool.k_pages[i] = jax.device_put(pool.k_pages[i],
                                             self._pool_sharding)
            pool.v_pages[i] = jax.device_put(pool.v_pages[i],
                                             self._pool_sharding)

    def _canon_pairs(self, pairs, pool):
        """Re-pin returned pools to the canonical sharding before they
        re-enter the cache: the sharded decode step already returns them
        committed there (free), while prefill/chunk/spec outputs carry
        whatever placement GSPMD inferred and reshard once here — so the
        next decode dispatch always sees one stable input sharding and
        never retraces."""
        if (self._pool_sharding is None
                or pool.num_kv_heads % self.tp_degree):
            return pairs
        return [(jax.device_put(k, self._pool_sharding),
                 jax.device_put(v, self._pool_sharding))
                for k, v in pairs]

    def _store(self, states) -> None:
        self.pool.install_pools(self._canon_pairs(
            [(_val(st.k_pages), _val(st.v_pages)) for st in states],
            self.pool))

    def _admit_shared(self, req: Request, slot: int, pages: List[int],
                      n_cached: int) -> None:
        """Prefix-cache admission: adopt the cached prompt pages
        read-only — the cached portion's prefill compute is skipped
        entirely. A SHORT remaining suffix teacher-forces through the
        ordinary decode step (one token per engine step, no extra
        program: the model output while suffix tokens are pending is a
        prompt-position logit and is discarded; the step that feeds the
        LAST suffix token emits the first generated token). A LONG
        suffix, with chunking enabled, prefills from the adopted-prefix
        cursor in chunks instead — the chunk program natively starts at
        a nonzero position."""
        self.pool.adopt_shared(slot, pages)
        if self._prefix is not None:
            # pin count on adoption: evict() must never free pages an
            # in-flight request's block table still points at
            self._prefix.pin(pages)
            req.pinned = [int(p) for p in pages]
        self.pool.seq_lens[slot] = n_cached
        suffix = req.prompt[n_cached:]
        self.pool.allocate(slot, len(suffix) + req.max_new_tokens)
        if self.chunk and len(suffix) > 2 * self.pool.page_size:
            req.feed = req.prompt
            req.prefill_pos = n_cached
        else:
            self._last_tok[slot] = int(suffix[0])
            req.pending = [int(t) for t in suffix[1:]]
        req.slot = slot
        self._slots[slot] = req
        self._m.shared_admits.inc()

    def _covers_enough(self, req: Request, n_cached: int) -> bool:
        """The monolithic-mode coverage threshold: the suffix replays
        one token per decode step, so a barely-covered long prompt
        would trade one b=1 prefill for hundreds of full-batch steps.
        With chunking on, long suffixes prefill in chunks from the
        adopted cursor instead, so ANY hit is worth taking (callers
        short-circuit on ``self.chunk``)."""
        return (len(req.prompt) - n_cached
                <= max(2 * self.pool.page_size, n_cached))

    def _hit_worth_taking(self, req: Request) -> bool:
        """Would ``_admit`` accept this request's prefix hit? Mirrored
        on POTENTIAL coverage (spilled pages included) BEFORE lookup
        runs: with chunking off, a hit the coverage threshold refuses
        must be detected up front, or lookup's restores would consume
        free pages ``_next_admission`` never priced — the subsequent
        full-span allocate could exhaust the pool mid-step."""
        if self.chunk:
            return True
        n = self._prefix.peek(req.prompt, include_spilled=True)
        while n >= len(req.prompt):
            n -= self.pool.page_size
        return n > 0 and self._covers_enough(req, n)

    def _admission_feed(self, req: Request) -> np.ndarray:
        """What prefill teacher-forces for this admission. First
        admission: the prompt. Replay admission (recovery re-queued an
        in-flight request): prompt + every already-emitted token — all
        host-side state — so the b=1 prefill reconstructs the KV cache
        and its argmax IS the next greedy token. Greedy decoding makes
        the replayed continuation identical to the uninterrupted one."""
        if not req.tokens:
            return req.prompt
        return np.concatenate(
            [req.prompt, np.asarray(req.tokens, np.int32)])

    def _admit(self, req: Request, slot: int) -> bool:
        """Route one admission: prefix-cache shared adoption when the
        prompt's pages already live in the pool, the chunked-prefill
        cursor for long prompts, the classic monolithic b=1 prefill
        otherwise."""
        # queued phase closes at admission: submit() -> here (once per
        # REQUEST, not per token)  # tracecheck: disable=TRC007
        self._m.event("request.queued", req.t_submit, time.perf_counter(),
                      rid=req.rid)
        replay = bool(req.tokens)
        if self._prefix is not None and not replay \
                and self._hit_worth_taking(req):
            # max_cover never covers the WHOLE prompt: the first
            # generated token's logits are not cached, so at least one
            # prompt token must go through compute — and lookup must
            # not restore a spilled page an over-cover would discard
            pages, n_cached = self._prefix.lookup(
                req.prompt, max_cover=len(req.prompt) - 1)
            if pages and (self.chunk or self._covers_enough(
                    req, n_cached)):
                self._admit_shared(req, slot, pages, n_cached)
                return False    # no prefill compute dispatched
        feed = self._admission_feed(req)
        if self.chunk and len(feed) > self.chunk:
            # chunked admission: allocate the full page span now, then
            # prefill one chunk per step() so decode never stalls for
            # more than one chunk
            remaining = req.max_new_tokens - len(req.tokens)
            self.pool.allocate(slot, len(feed) + remaining)
            req.feed = feed
            req.prefill_pos = 0
            req.slot = slot
            self._slots[slot] = req
            return False    # chunks dispatch one per step, not here
        self._prefill(req, slot, feed)
        return True         # monolithic prefill compute ran this step

    def _prefill(self, req: Request, slot: int,
                 feed: Optional[np.ndarray] = None) -> None:
        """Monolithic b=1 whole-prompt prefill (prompts at or under the
        chunk size, and every prompt when chunking is off)."""
        replay = bool(req.tokens)
        if feed is None:
            feed = self._admission_feed(req)
        p = len(feed)
        # the cached prefill program: jit itself caches one compilation
        # per prompt length (bucket/pad prompts in production to bound
        # that set); the program-cache layer shares those compilations
        # across engine instances over the same model
        fn = self._prefill_program()

        remaining = req.max_new_tokens - len(req.tokens)
        self.pool.allocate(slot, p + remaining)
        bt = jnp.asarray(self.pool.block_tables[slot:slot + 1])
        # per-request prefill timeline span  # tracecheck: disable=TRC007
        with self._m.span("request.prefill", rid=req.rid, prompt_len=p):
            pools = self.pool.take_pools()
            self._f_prefill.check()
            tok, states = fn(self._params, self._buffers,
                             jnp.asarray(feed[None]),
                             pools, bt, jnp.zeros((1,), jnp.int32))
            # b=1 prefill wrote THROUGH slot's block table into the
            # shared pool arrays; adopt them and the slot's bookkeeping
            self._store(states)
            tok = int(tok)              # the span owns the token pull
        # once per admitted request  # tracecheck: disable=TRC007
        self._m.prefills.inc()
        if req.temperature > 0.0:
            # a sampled request never takes the prefill's greedy argmax:
            # park the cursor ONE position short with the last fed token
            # as the pending feed — exactly the spec-round entry
            # invariant, so the verify program samples the position the
            # prefill would have decided (and a replayed admission
            # resumes at the SAME position key, redrawing identically)
            self.pool.seq_lens[slot] = p - 1
            self._last_tok[slot] = int(feed[-1])
            req.slot = slot
            self._slots[slot] = req
            if self._prefix is not None and not replay:
                self._prefix.register(req.prompt,
                                      self.pool.block_tables[slot])
            return
        self.pool.seq_lens[slot] = p
        self._last_tok[slot] = tok
        tnow = time.perf_counter()
        if replay:
            # the replayed prefill's token continues the sequence: its
            # latency is inter-token, not a second TTFT
            # tracecheck: disable=TRC007
            self._m.itl.observe(tnow - req.t_last)
        else:
            # TTFT closes on the prefill's token
            # tracecheck: disable=TRC007
            self._m.ttft.observe(tnow - req.t_submit)
        req.t_last = tnow
        req.tokens.append(tok)
        self._emit(req, tok)
        req.slot = slot
        self._slots[slot] = req
        if self._prefix is not None and not replay:
            # pin this prompt's full pages for future shared admissions
            # (they are immutable: later writes land at seq_len and up)
            self._prefix.register(req.prompt, self.pool.block_tables[slot])
        self._finish_if_done(req)

    def _prefill_chunk(self, req: Request) -> None:
        """One chunk of one mid-prefill request: write ``chunk`` tokens
        of its feed into the KV pool at the cursor and advance it. Every
        chunk dispatches the SAME cached (1, chunk) program — the final
        partial chunk pads (pad KV is causally masked and pad positions
        past the block table drop), and only the final chunk's argmax is
        pulled to the host: it is the request's first generated token
        (or, on replay, the next greedy continuation token)."""
        feed, pos, c = req.feed, req.prefill_pos, self.chunk
        end = min(pos + c, len(feed))
        last = end == len(feed)
        ids = np.zeros((c,), np.int32)
        ids[:end - pos] = feed[pos:end]
        fn = self._chunk_program()
        slot = req.slot
        bt = jnp.asarray(self.pool.block_tables[slot:slot + 1])
        sl = jnp.asarray(np.full((1,), pos, np.int32))
        t0 = time.perf_counter() if self._m.enabled else 0.0
        pools = self.pool.take_pools()
        self._f_chunk.check()
        tok, states = fn(self._params, self._buffers,
                         jnp.asarray(ids[None]), pools, bt, sl,
                         jnp.int32(end - pos - 1))
        self._store(states)
        self.pool.seq_lens[slot] = end
        req.prefill_pos = end
        self.chunk_dispatches += 1
        if not last:
            # the non-final argmax is garbage-padded and never pulled:
            # the dispatch stays async  # tracecheck: disable=TRC007
            self._observe_chunk(time.perf_counter() - t0)
            return
        tok = int(tok)      # designed sync: the first generated token
        tnow = time.perf_counter()
        self._observe_chunk(tnow - t0, final=True)
        replay = bool(req.tokens)
        if req.temperature > 0.0:
            # sampled request: discard the final chunk's greedy argmax
            # and park the cursor one short (see _prefill) — the spec
            # verify program is the only sampler
            self.pool.seq_lens[slot] = len(feed) - 1
            self._last_tok[slot] = int(feed[-1])
            req.prefill_pos = None
            req.feed = None
            if self._prefix is not None and not replay:
                self._prefix.register(req.prompt,
                                      self.pool.block_tables[slot])
            return
        if replay:
            # a replayed prefill's token continues the sequence: its
            # latency is inter-token, not a second TTFT
            # tracecheck: disable=TRC007
            self._m.itl.observe(tnow - req.t_last)
        else:
            # TTFT closes on the final chunk's token
            # tracecheck: disable=TRC007
            self._m.ttft.observe(tnow - req.t_submit)
        req.t_last = tnow
        req.tokens.append(tok)
        self._emit(req, tok)
        self._last_tok[slot] = tok
        req.prefill_pos = None
        req.feed = None
        if self._prefix is not None and not replay:
            # the whole prompt's KV is now written (adopted prefix +
            # chunked suffix): register its full pages — repeats of
            # this prompt deepen the cache
            self._prefix.register(req.prompt, self.pool.block_tables[slot])
        self._finish_if_done(req)

    def _chunk_step(self) -> bool:
        """At most ONE prefill chunk per engine step — the stall a
        long-prompt arrival can impose on decoding requests is bounded
        by one chunk, never a whole prompt. Among mid-prefill requests
        the scheduler order (deadline slack, then FIFO) picks."""
        cands = [r for r in self._slots
                 if r is not None and r.prefill_pos is not None]
        if not cands:
            return False
        now = time.perf_counter()
        req = min(cands, key=lambda r: self._slack_key(r, now))
        self._prefill_chunk(req)
        return True

    def _to_replay_form(self, req: Request, unpin: bool = True) -> None:
        """Reset a request's per-admission transient state to pure
        replay form (prompt + emitted tokens drive any re-admission).
        Every path that detaches a live request funnels through here —
        terminal finalize, replay recovery, SLO preemption, fleet
        export — so a new transient field added to ``Request`` gets its
        reset in ONE place instead of four. ``unpin=False`` when the
        pool the pins indexed is already dead (recovery rebuilt pool
        AND prefix cache; the fresh cache never saw those pages)."""
        if unpin and req.pinned and self._prefix is not None:
            self._prefix.unpin(req.pinned)
        if req.spec_ready:
            # release the draft pool's mirror allocation when that pool
            # is still alive (recovery rebuilds it fresh, so a freshly
            # rebuilt or detached pool has nothing of ours to free);
            # gamma/spec_ema deliberately survive — the draft's observed
            # agreement is the request's property, not the admission's
            if (req.slot is not None and self._draft_pool is not None
                    and self._draft_pool.k_pages[0] is not None):
                self._draft_pool.free_sequence(req.slot)
            req.spec_ready = False
        req.pinned = []
        req.pending = []
        req.prefill_pos = None
        req.feed = None
        req.slot = None
        req.bypassed = 0

    def _emit(self, req: Request, tok: Optional[int],
              done: bool = False) -> None:
        """Buffer one streaming event; :meth:`step` drains the buffer
        to the callbacks after dispatch/recovery completes."""
        cb = self._callbacks.get(req.rid)
        if cb is not None:
            self._events.append((cb, req.rid, tok, done))

    def _drain_events(self) -> None:
        while self._events:
            cb, rid, tok, done = self._events.pop(0)
            cb(rid, tok, done)

    def _finalize(self, req: Request, status: str,
                  error: Optional[str] = None) -> None:
        """Terminal bookkeeping shared by every way a request ends:
        release its slot/pages/pins, bank its tokens (partial for
        FAILED/TIMEOUT) and record the status. Pure host state — no
        telemetry here (callers observe through ``_observe_*``)."""
        if req.slot is not None:
            self.pool.free_sequence(req.slot)
            self._slots[req.slot] = None
        self._to_replay_form(req)
        req.status = status
        req.error = error
        self._results[req.rid] = req.tokens
        self._status[req.rid] = status
        self._emit(req, None, done=True)
        # the terminal event is buffered above with the callback object
        # in hand; the registry entry is dead weight from here on
        self._callbacks.pop(req.rid, None)

    def _finish_if_done(self, req: Request) -> None:
        done = len(req.tokens) >= req.max_new_tokens or (
            req.eos_token_id is not None
            and req.tokens and req.tokens[-1] == req.eos_token_id)
        if done and req.slot is not None:
            self._finalize(req, OK)
            # once per finished request  # tracecheck: disable=TRC007
            self._m.finished.inc()
            if self._m.enabled:
                # lifecycle close event  # tracecheck: disable=TRC007
                self._m.event("request.complete", req.t_submit,
                              time.perf_counter(), rid=req.rid,
                              tokens=len(req.tokens))

    def _sweep_deadlines(self) -> None:
        """Step-boundary deadline enforcement: terminate every queued or
        in-flight request past its ``submit(deadline=...)`` cutoff with
        status TIMEOUT and its partial tokens banked."""
        now = time.perf_counter()
        expired = [r for r in self._slots
                   if r is not None and r.deadline is not None
                   and now > r.deadline]
        expired += [r for r in self._queue
                    if r.deadline is not None and now > r.deadline]
        if not expired:
            return
        rids = {r.rid for r in expired}
        self._queue = [r for r in self._queue if r.rid not in rids]
        for req in expired:
            self._finalize(req, TIMEOUT, "deadline exceeded")
        self._observe_timeouts(len(expired))

    def _expire_all(self, why: str) -> None:
        """The ``run(max_wall=...)`` watchdog tripped: terminate every
        remaining request TIMEOUT instead of spinning forever."""
        remaining = [r for r in self._slots if r is not None]
        remaining += list(self._queue)
        self._queue = []
        for req in remaining:
            self._finalize(req, TIMEOUT, why)
        if remaining:
            self._observe_timeouts(len(remaining))
        self._observe_step_end()

    def step(self) -> None:  # tracecheck: hotpath
        """One scheduler round: deadline sweep, bucket migration,
        admission, at most one prefill chunk, one decode dispatch. A
        failed dispatch does NOT propagate — replay recovery (fresh
        pools, re-queue of all in-flight requests, bounded retries with
        exponential backoff) runs instead, and requests only ever end
        in a terminal OK/FAILED/TIMEOUT status. Streaming callbacks
        drain LAST, outside the recovery boundary: a raising callback
        surfaces to the caller, never as a fake dispatch failure."""
        try:
            self._step_inner()
            self._consec_failures = 0
        except Exception as exc:
            self._recover_dispatch(exc)
        finally:
            self._drain_events()

    def _recover_dispatch(self, exc: Exception) -> None:
        """Replay recovery. The donated dispatch died, so the pool is
        already detached (r08 discipline) and its device buffers are
        unrecoverable — but every request's prompt AND emitted tokens
        are host-side state. Allocate fresh pools, terminate requests
        whose no-progress retry budget is exhausted, re-queue the rest
        for re-prefill from prompt + emitted tokens (greedy decoding
        makes the replayed continuation bit-identical), and back off
        exponentially while nothing progresses."""
        t0 = time.perf_counter()
        live = [r for r in self._slots if r is not None]
        failed_adm = self._failed_admission
        self._failed_admission = None
        # a failed admission was rolled back before the raise, so it is
        # never also in a slot
        victims = live + ([failed_adm] if failed_adm is not None else [])
        if not victims:
            if self._queue and self._consec_failures < self.max_retries:
                # nothing in flight died but work is queued — e.g. a
                # bucket-migration fault BEFORE admission. No request
                # state was lost, so back off and press on; the
                # engine-wide no-progress budget still bounds this, so
                # a real scheduler bookkeeping bug surfaces loudly
                # after max_retries consecutive failures instead of
                # spinning forever.
                if (self.pool.k_pages and self.pool.k_pages[0] is None) \
                        or (self._draft_pool is not None
                            and self._draft_pool.k_pages[0] is None):
                    self._rebuild_pool()    # a detached pool stays dead
                self._consec_failures += 1
                self._observe_recovery(0, 0, time.perf_counter() - t0)
                time.sleep(min(
                    self.retry_backoff * (2 ** (self._consec_failures - 1)),
                    2.0))
                return
            # nothing was in flight and nothing is queued (or the
            # budget is spent): this is not a dispatch failure the
            # replay machinery can absorb — a bookkeeping error must
            # stay loud (results so far remain retrievable, see
            # ``results()``)
            raise exc
        self._rebuild_pool()
        survivors: List[Request] = []
        failed: List[Request] = []
        any_progress = False
        for req in victims:
            # progress is (tokens, prefill cursor): a long prompt's
            # chunks count as progress before any token exists, so a
            # transient mid-prefill fault doesn't eat the retry budget.
            # The mark is a HIGH-WATER mark — it never moves backwards:
            # the cursor resets to 0 on every replay, and an oscillating
            # failure point below the best attempt must not read as
            # fresh progress or a persistently flaky backend could
            # reset the retry budget forever.
            progress = (len(req.tokens), req.prefill_pos or 0)
            # unpin=False: the pinned pages died with the old pool and
            # the rebuilt prefix cache never saw them; replay
            # re-prefills from host state (prompt + tokens)
            self._to_replay_form(req, unpin=False)
            if progress > req.progress_mark:
                any_progress = True
                req.retries = 1
                req.progress_mark = progress
            else:
                req.retries += 1
            if req.retries > self.max_retries:
                failed.append(req)
            else:
                survivors.append(req)
        self._slots = [None] * self.max_batch
        self._last_tok[:] = 0
        for req in failed:
            self._finalize(req, FAILED, repr(exc))
        # replays keep their submission order relative to the queue
        self._queue = sorted(survivors + self._queue,
                             key=lambda r: r.rid)
        self._consec_failures = (1 if any_progress
                                 else self._consec_failures + 1)
        self._observe_recovery(len(survivors), len(failed),
                               time.perf_counter() - t0)
        if self._queue:
            time.sleep(min(
                self.retry_backoff * (2 ** (self._consec_failures - 1)),
                2.0))

    def _rebuild_pool(self) -> None:
        """Fresh pools with the identical geometry, so the already-
        compiled prefill/decode programs (keyed on that geometry) serve
        the replays without a retrace. The prefix cache indexed pages of
        the dead pool and restarts empty."""
        self.pool = PagedKVCache(**self._pool_geom)
        self._shard_pool(self.pool)
        if self._draft_pool is not None:
            # the draft pool dies with the target's (a spec fault leaves
            # one detached, and a rebuilt target invalidates the draft's
            # cursor lockstep either way); replay re-syncs from host
            # state through the draft chunk program
            self._draft_pool = PagedKVCache(**self._draft_geom)
            self._shard_pool(self._draft_pool)
        self._prefix = (PrefixCache(self.pool, replica=self.replica,
                                    host_tier_pages=self.host_tier_pages)
                        if self._prefix_enabled else None)
        self._pool_frag_epoch = -1      # fresh pool: re-publish ledger

    def _rollback_admission(self, req: Request, slot: int) -> None:
        """Undo a partial admission (page exhaustion mid-``allocate``):
        return the slot's pages, drop adopted pins, clear teacher-forced
        state — the request goes back to the queue head intact."""
        self.pool.free_sequence(slot)
        if req.pinned and self._prefix is not None:
            self._prefix.unpin(req.pinned)
        req.pinned = []
        req.pending = []
        req.prefill_pos = None
        req.feed = None
        req.slot = None
        self._slots[slot] = None

    # ---------------------------------------------------- the scheduler
    _BYPASS_BUDGET = 4   # cached-prefix bypasses one blocked head allows
    _BYPASS_SCAN = 8     # queue depth scanned for a bypass candidate

    @staticmethod
    def _slack_key(req: Request, now: float):
        """Scheduler order: deadline slack ascending (tightest budget
        first); every no-deadline request ties at +inf, so among
        themselves they keep classic FIFO arrival order by rid."""
        slack = (req.deadline - now) if req.deadline is not None \
            else float("inf")
        return (slack, req.rid)

    def _pages_needed(self, req: Request) -> int:
        return -(-(len(req.prompt) + req.max_new_tokens)
                 // self.pool.page_size)

    def _admission_order(self) -> List[Request]:
        """This step's admission order, computed ONCE per step (slack
        depends only on the clock, not on pages, so the order is stable
        across the step's slot loop): deadline slack ascending, FIFO by
        rid among no-deadline ties."""
        now = time.perf_counter()
        return sorted(self._queue, key=lambda r: self._slack_key(r, now))

    def _shared_adopt_pages(self, req: Request) -> int:
        """Pages an admission of ``req`` would adopt read-only from the
        prefix cache (0 = it would NOT take the shared route). The one
        probe that mirrors ``_admit``'s actual routing — a probe that
        disagrees with ``_admit`` would misprice admissions: replays
        never share, a whole-prompt hit trims, and chunking-off mode
        applies the coverage threshold."""
        if self._prefix is None or req.tokens:
            return 0
        memo = self._probe_memo.get(req.rid)
        if memo is not None:
            return memo
        n = self._prefix.peek(req.prompt)
        while n >= len(req.prompt):
            n -= self.pool.page_size
        if n <= 0 or (not self.chunk
                      and not self._covers_enough(req, n)):
            n = 0           # miss, or _admit's monolithic coverage
                            # threshold would refuse the hit
        pages = n // self.pool.page_size
        self._probe_memo[req.rid] = pages
        return pages

    def _fresh_pages_needed(self, req: Request) -> int:
        """Fresh (free-list) pages admitting ``req`` costs right now —
        total span minus whatever its cached prefix supplies."""
        return self._pages_needed(req) - self._shared_adopt_pages(req)

    def _needs_prefill_unit(self, req: Request) -> bool:
        """Would admitting ``req`` dispatch a monolithic prefill — the
        step's single prefill-compute unit? Shared adoptions and
        chunked admissions are cursor-only host bookkeeping."""
        if self._shared_adopt_pages(req):
            return False
        if self.chunk and len(req.prompt) + len(req.tokens) > self.chunk:
            return False
        return True

    def _next_admission(self, order: List[Request]) -> Optional[Request]:
        """The next request to admit from this step's ``order``, or
        None when admission must wait. The slack head goes first; a
        page-blocked head first reclaims cached-but-unshared pages
        (evict), then may be BYPASSED — boundedly, so it never starves
        — by a request whose prompt prefix already lives in the prefix
        cache: that request admits onto pages it shares instead of
        fresh ones, so it lands where its pages already live without
        consuming the head's."""
        head = order[0]
        self._head_blocked = False
        # the head's page bill is its FRESH need: a head whose prompt
        # prefix already lives in the cache admits onto shared pages
        # and only pays for the suffix — gating it on the full span
        # would declare an admittable head blocked (and eviction could
        # even cannibalize its own cached prefix)
        need = self._fresh_pages_needed(head)
        if need > self.pool.free_page_count() and self._prefix:
            # cached-but-unshared pages are reclaimable capacity;
            # a shortfall (pinned/shared pages refusing eviction)
            # is banked as pressure, not silently swallowed
            want = need - self.pool.free_page_count()
            freed = self._prefix.evict(want)
            if freed < want:
                self._observe_evict_shortfall(want - freed)
            # eviction mutates the trie — LRU may even have dropped
            # part of the HEAD's own cached prefix — so its bill must
            # be repriced, not tested against the stale estimate
            self._probe_memo.clear()
            need = self._fresh_pages_needed(head)
        if need <= self.pool.free_page_count():
            return head
        # graceful degradation: the head WAITS in the queue (no
        # starvation) and the shortfall is published as pressure
        self._head_blocked = True
        self._observe_page_pressure(need - self.pool.free_page_count())
        if self._prefix is not None and head.bypassed < self._BYPASS_BUDGET:
            for req in order[1:1 + self._BYPASS_SCAN]:
                adopt = self._shared_adopt_pages(req)
                if adopt and (self._pages_needed(req) - adopt
                              <= self.pool.free_page_count()):
                    head.bypassed += 1
                    return req
        return None

    def _maybe_migrate(self, order: List[Request]) -> None:
        """Bucket-ladder control: pick the smallest rung covering
        current demand, capped at the top rung. Demand counts only
        queued work the page pool could actually admit, scanned in the
        SAME deadline-slack order admission uses (head-of-line on that
        order) — a page-BLOCKED queue must not inflate the bucket to
        rungs whose slots can never fill, where every decode step would
        pay for idle rows. Growth is immediate — admittable work is
        waiting; shrink waits out
        ``FLAGS_serving_bucket_patience`` steps of sustained lower
        demand so occupancy flapping never thrashes programs."""
        if len(self.ladder) == 1:
            return
        active = sum(1 for r in self._slots if r is not None)
        free = self.pool.free_page_count()
        admittable = 0
        for req in order[:self.max_batch]:
            need = self._fresh_pages_needed(req)
            if need > free:
                break
            free -= need
            admittable += 1
        demand = max(1, min(active + admittable, self.max_batch))
        target = next(r for r in self.ladder if r >= demand)
        if target > self.bucket:
            self._migrate(target)
            self._shrink_wait = 0
        elif target < self.bucket:
            self._shrink_wait += 1
            if self._shrink_wait >= self.bucket_patience:
                self._migrate(target)
                self._shrink_wait = 0
        else:
            self._shrink_wait = 0

    def _migrate(self, target: int) -> None:
        """Move the decode batch to rung ``target``: shrinking compacts
        the active sequences into the low slots (pure host-side
        block-table row moves — KV pages never copy), growing just
        widens the next dispatch. Each rung's program compiles once and
        stays cached, so steady-state migration is retrace-free."""
        self._f_migrate.check(phase="begin", frm=self.bucket, to=target)
        if target < self.bucket:
            dst = 0
            for s in range(target, self.max_batch):
                req = self._slots[s]
                if req is None:
                    continue
                while self._slots[dst] is not None:
                    dst += 1        # always < target: target covers active
                self.pool.move_sequence(s, dst)
                if req.spec_ready:
                    # the draft pool mirrors the target's slot layout
                    self._draft_pool.move_sequence(s, dst)
                self._last_tok[dst] = self._last_tok[s]
                self._slots[dst] = req
                self._slots[s] = None
                req.slot = dst
                # deliberately MID-mutation: every=N drills must land
                # between row moves, and recovery replays the whole
                # batch from host state so no half-compacted table
                # survives  # faultcheck: disable=FLT002
                self._f_migrate.check(phase="move", rid=req.rid)
        self.bucket = target
        self.bucket_migrations += 1
        # post-commit schedule point, same full-replay argument
        # faultcheck: disable=FLT002
        self._f_migrate.check(phase="commit")
        self._observe_bucket(migrated=True)

    # ------------------------------------------------ SLO preemption
    def _preempt_for(self, order: List[Request]) -> None:
        """Bounded eviction of running work for an ENDANGERED deadline:
        when the tightest-slack waiting request (a) has a deadline with
        slack already inside ``FLAGS_serving_preempt_horizon``, and (b)
        cannot admit — every slot is occupied, or its fresh-page bill
        exceeds free + evictable pages — unseat the SLACKEST running
        request whose slack exceeds the head's by at least the margin.
        The victim goes back to the queue intact (prompt + emitted
        tokens are host state) and its later re-admission replays the
        r10 recovery path, so the resumed greedy continuation is
        bit-identical; each victim is preemptible at most
        ``FLAGS_serving_preempt_budget`` times, and preemptions never
        touch the replay-recovery retry budget."""
        if not self.preempt_enabled or not order:
            return
        head = order[0]
        if head.deadline is None:
            return                  # only deadline pressure preempts
        now = time.perf_counter()
        head_slack = head.deadline - now
        if head_slack > self.preempt_horizon:
            return                  # comfortable slack: wait in line
        while True:
            # free slots within the CURRENT bucket rung: the fill loop
            # only admits into slots below self.bucket, and a ladder's
            # out-of-rung slots are always None — counting those would
            # read a saturated rung as admittable and never preempt
            # (migration can't grow the rung either: a page-blocked
            # head is not "admittable demand")
            free_slots = self._slots[:self.bucket].count(None)
            need = self._fresh_pages_needed(head)
            reclaimable = (self.pool.free_page_count()
                           + (self._prefix.evictable_page_count()
                              if self._prefix is not None else 0))
            if free_slots and need <= reclaimable:
                return              # admittable without a victim
            cands = [r for r in self._slots
                     if r is not None and r.rid != head.rid
                     and r.preempts < self.preempt_budget]
            victim = None
            best = (-1.0, -1)
            # STRICTLY slacker than head + margin: an equal-slack pair
            # must never swap seats (each swap replays a healthy
            # request's whole prefill for zero deadline benefit)
            for r in cands:
                slack = ((r.deadline - now) if r.deadline is not None
                         else float("inf"))
                if slack <= head_slack + self.preempt_margin:
                    continue
                if (slack, r.rid) > best:
                    best = (slack, r.rid)
                    victim = r
            if victim is None:
                return              # nobody meaningfully slacker
            # fault check BEFORE any mutation: an injected preemption
            # failure propagates into replay recovery cleanly
            self._f_preempt.check(rid=victim.rid)
            self._unseat(victim)
            # pages moved: reprice the head's bill before looping
            self._probe_memo.clear()

    def _unseat(self, req: Request) -> None:
        """Return one RUNNING request to the queue as pure host state —
        the preemption primitive. Slot, pages and pins release; tokens
        and the deadline stay; admission later replays it from prompt +
        emitted tokens (greedy => bit-identical continuation)."""
        slot = req.slot
        self.pool.free_sequence(slot)
        self._slots[slot] = None
        self._last_tok[slot] = 0
        self._to_replay_form(req)
        req.preempts += 1
        self.preemptions += 1
        self._queue.append(req)
        self._observe_preemption(req)

    # ------------------------------------------- speculative decoding
    # One round = one draft-propose dispatch (γ+1 draft forwards inside
    # a lax.scan) + one target-verify dispatch (a (1, γ+1) chunk of the
    # r12 chunked-prefill machinery). Losslessness rests ONLY on the
    # verify: draft writes past the accepted length — even past the
    # allocated span, where unallocated block-table entries route to
    # the reserved null scribble page — are garbage a later dispatch
    # overwrites before any real row attends to it, so γ needs no
    # tail-fitting constraint (new tokens just truncate to the budget).

    def _store_draft(self, states) -> None:
        self._draft_pool.install_pools(self._canon_pairs(
            [(_val(st.k_pages), _val(st.v_pages)) for st in states],
            self._draft_pool))

    def _spec_occupancy_cap(self, n_rows: int) -> int:
        """Largest γ rung the decode-slot budget affords with
        ``n_rows`` speculating rows, each billed γ+1 slots (its verify
        covers γ+1 positions — the bucket-ladder admission price of a
        speculating request). 0 = priced out: at this occupancy the
        plain batched decode step is the cheaper schedule."""
        for g in reversed(self.spec_rungs):
            if n_rows * (g + 1) <= self.spec_slots:
                return g
        return 0

    def _spec_gamma(self, req: Request, cap: int) -> int:
        """This round's γ for one request: its adaptive rung, capped by
        occupancy and snapped DOWN to a compiled rung (never retrace),
        then trimmed toward the tail of the token budget so the last
        round doesn't draft far past ``max_new_tokens`` (truncation
        keeps correctness either way; this keeps the draft cheap)."""
        g = req.gamma or self.spec_gamma_default
        if cap:
            g = min(g, cap)
        remaining = req.max_new_tokens - len(req.tokens)
        fit = [r for r in self.spec_rungs
               if r <= min(g, max(1, remaining - 1))]
        return fit[-1] if fit else self.spec_rungs[0]

    def _spec_step(self, rows: List[Request]) -> bool:
        """Serve this step's decode-ready rows through speculation
        rounds, or decline (return False) and let the plain batched
        decode run. All-or-nothing per step: a row still teacher-
        forcing a prompt suffix (``pending``) keeps the whole step on
        the plain path (the suffix feed IS the plain step), and a step
        whose occupancy prices speculation out declines too — UNLESS a
        sampled request is present: sampling only exists through the
        verify program's rejection sampler, so sampled rows force
        speculation (at the smallest rung when over the budget)."""
        if any(r.pending for r in rows):
            return False
        sampled = any(r.temperature > 0.0 for r in rows)
        cap = self._spec_occupancy_cap(len(rows))
        if cap == 0 and not sampled:
            return False
        for req in list(rows):
            self._spec_round(req, self._spec_gamma(req, cap))
        return True

    def _spec_sync(self, req: Request) -> None:
        """Bring the draft pool's KV for this slot up to the target's
        accepted length L. First entry allocates the slot's full span
        (the worst-case draft pool makes that infallible); any cursor
        gap — admission prefilled the target only, or plain decode
        advanced it while speculation was priced out — teacher-forces
        through the draft's chunked-prefill program in fixed (1, C)
        chunks whose argmax is never pulled, so sync never retraces
        and never blocks on a device value."""
        slot = req.slot
        L = int(self.pool.seq_lens[slot])
        if not req.spec_ready:
            self._draft_pool.allocate(
                slot, L + 1 + req.max_new_tokens - len(req.tokens))
            req.spec_ready = True
        cur = int(self._draft_pool.seq_lens[slot])
        if cur >= L:
            return
        feed = np.concatenate(
            [req.prompt, np.asarray(req.tokens, np.int32)])
        width = self.spec_sync_chunk
        fn = self._spec_sync_program()
        while cur < L:
            end = min(cur + width, L)
            ids = np.zeros((width,), np.int32)
            ids[:end - cur] = feed[cur:end]
            bt = jnp.asarray(
                self._draft_pool.block_tables[slot:slot + 1])
            sl = jnp.asarray(np.full((1,), cur, np.int32))
            dpools = self._draft_pool.take_pools()
            self._f_spec_draft.check(rid=req.rid, op="sync")
            _tok, states = fn(self._draft_params, self._draft_buffers,
                              jnp.asarray(ids[None]), dpools, bt, sl,
                              jnp.int32(end - cur - 1))
            self._store_draft(states)
            cur = end
        self._draft_pool.seq_lens[slot] = L

    def _spec_round(self, req: Request, gamma: int) -> None:
        """One propose/verify round for one decode-ready request.
        Round invariant (both pools, entering and leaving): the KV
        holds ids[:L] and ``_last_tok`` is ids[L], the newest not-yet-
        written token. The draft scan runs γ+1 forwards — the extra
        one writes the last proposal's KV — so a fully-accepted round
        leaves the draft cache gap-free and the next round needs no
        catch-up dispatch. Both fault sites fire BEFORE the accepted-
        length cursor roll (FLT002): an injected fault replays the
        round from host state bit-identically."""
        slot = req.slot
        sample = req.temperature > 0.0
        self._spec_sync(req)
        L = int(self.pool.seq_lens[slot])
        t0 = time.perf_counter() if self._m.enabled else 0.0
        # --- draft: γ proposals in ONE dispatch
        dfn = self._spec_draft_program(gamma, sample, req.top_k)
        dbt = jnp.asarray(self._draft_pool.block_tables[slot:slot + 1])
        dsl = jnp.asarray(self._draft_pool.seq_lens[slot:slot + 1])
        tok = jnp.asarray(self._last_tok[slot:slot + 1][:, None])
        dpools = self._draft_pool.take_pools()
        self._f_spec_draft.check(rid=req.rid, op="draft")
        if sample:
            key = jax.random.PRNGKey(
                (req.seed * 1000003 + L) & 0x7FFFFFFF)
            props, qrows, dstates = dfn(
                self._draft_params, self._draft_buffers, tok, dpools,
                dbt, dsl, key, jnp.float32(req.temperature),
                jnp.float32(req.top_p))
        else:
            qrows = None
            props, dstates = dfn(self._draft_params,
                                 self._draft_buffers, tok, dpools,
                                 dbt, dsl)
        self._store_draft(dstates)
        # the verify chunk's ids need the concrete proposals — the
        # round's one designed draft->host sync point
        props_np = np.asarray(props).astype(np.int32).reshape(-1)
        # --- verify: ONE (1, γ+1) chunk through the TARGET
        ids = np.empty((gamma + 1,), np.int32)
        ids[0] = self._last_tok[slot]
        ids[1:] = props_np[:gamma]
        vfn = self._spec_verify_program(gamma, sample, req.top_k)
        bt = jnp.asarray(self.pool.block_tables[slot:slot + 1])
        sl = jnp.asarray(self.pool.seq_lens[slot:slot + 1])
        pools = self.pool.take_pools()
        self._f_spec_verify.check(rid=req.rid)
        if sample:
            greedy, prows, states = vfn(
                self._params, self._buffers, jnp.asarray(ids[None]),
                pools, bt, sl, jnp.float32(req.temperature),
                jnp.float32(req.top_p))
        else:
            prows = None
            greedy, states = vfn(self._params, self._buffers,
                                 jnp.asarray(ids[None]), pools, bt, sl)
        self._store(states)
        # --- acceptance (host): longest agreeing prefix + correction
        if sample:
            new_toks, accepted = self._spec_accept_sample(
                req, L, gamma, props_np, np.asarray(qrows),
                np.asarray(prows))
        else:
            greedy_np = np.asarray(greedy).reshape(-1)
            accepted = 0
            while accepted < gamma and \
                    int(props_np[accepted]) == int(greedy_np[accepted]):
                accepted += 1
            new_toks = [int(t) for t in props_np[:accepted]]
            new_toks.append(int(greedy_np[accepted]))
        # clip to the token budget, and to the first EOS — the plain
        # engine would have stopped there, so later positions of this
        # round must never exist
        new_toks = new_toks[:req.max_new_tokens - len(req.tokens)]
        if req.eos_token_id is not None and req.eos_token_id in new_toks:
            new_toks = new_toks[:new_toks.index(req.eos_token_id) + 1]
        # --- cursor roll (the rollback contract): both pools advance
        # to EXACTLY the accepted length; the rejected tail's KV
        # positions hold stale writes the next dispatch overwrites
        # before anything attends to them
        self.pool.seq_lens[slot] = L + len(new_toks)
        self._draft_pool.seq_lens[slot] = L + len(new_toks)
        now = time.perf_counter() if self._m.enabled else 0.0
        first = not req.tokens
        if self._prefix is not None and first:
            # first generated token of a shared admission: the verify
            # chunk just wrote the last prompt position — register the
            # full pages so repeats of this prompt deepen the cache
            self._prefix.register(req.prompt,
                                  self.pool.block_tables[slot])
        for t in new_toks:
            req.tokens.append(int(t))
            self._emit(req, int(t))
        if self._m.enabled:
            if first:
                # TTFT closes on the round's first token
                # tracecheck: disable=TRC007
                self._m.ttft.observe(now - req.t_submit)
            else:
                # ONE inter-token sample per round: a round delivers
                # its tokens as a burst, so the host-visible gap is the
                # round gap  # tracecheck: disable=TRC007
                self._m.itl.observe(now - req.t_last)
        req.t_last = now
        self._last_tok[slot] = int(new_toks[-1])
        # --- adaptive γ: accept-rate EMA moves the rung
        rate = accepted / gamma
        req.spec_ema = 0.7 * req.spec_ema + 0.3 * rate
        if self.spec_adaptive:
            idx = max(i for i, r in enumerate(self.spec_rungs)
                      if r <= max(gamma, self.spec_rungs[0]))
            if accepted == gamma and req.spec_ema >= self._SPEC_GROW:
                idx = min(idx + 1, len(self.spec_rungs) - 1)
            elif req.spec_ema < self._SPEC_SHRINK:
                idx = max(idx - 1, 0)
            req.gamma = self.spec_rungs[idx]
        else:
            req.gamma = gamma
        self.spec_rounds += 1
        self.spec_tokens_accepted += accepted
        self.spec_tokens_rejected += gamma - accepted
        self.spec_last_gamma = gamma
        self._observe_spec(gamma, accepted, rate, t0, now)
        self._finish_if_done(req)

    # accept-rate EMA thresholds of the adaptive-γ rung walk: grow only
    # on a sustained-high EMA *and* a clean round, shrink on sustained
    # low — the gap is the hysteresis band that stops rung flapping
    _SPEC_GROW = 0.75
    _SPEC_SHRINK = 0.35

    def _spec_accept_sample(self, req: Request, L: int, gamma: int,
                            props: np.ndarray, qrows: np.ndarray,
                            prows: np.ndarray):
        """Rejection sampling (the speculative-sampling identity):
        accept draft token d_i with probability min(1, p_i(d_i) /
        q_i(d_i)); on the first rejection draw the correction from the
        residual normalize(max(p_i - q_i, 0)); after a full accept
        draw the bonus token from the target's last row. p and q are
        the FILTERED (temperature/top-k/top-p) distributions the
        programs return, so the emitted law is exactly the target's
        sampling law. Uniforms come from default_rng((seed, L)) —
        position-keyed, so a replayed round at the same accepted
        length redraws identically and sampled recovery/preemption
        stays bit-identical. Returns (new_tokens, accepted_count)."""
        rng = np.random.default_rng((req.seed, L))
        out: List[int] = []
        for i in range(gamma):
            d = int(props[i])
            q = float(qrows[i, d])
            p = float(prows[i, d])
            if q <= 0.0 or rng.random() * q <= p:
                out.append(d)
                continue
            resid = np.maximum(
                prows[i].astype(np.float64) - qrows[i], 0.0)
            s = float(resid.sum())
            if s <= 0.0:        # q >= p everywhere (numerically): the
                resid = prows[i].astype(np.float64)     # target row
                s = float(resid.sum())                  # itself
            out.append(int(rng.choice(resid.shape[0], p=resid / s)))
            return out, i
        last = prows[gamma].astype(np.float64)
        out.append(int(rng.choice(last.shape[0], p=last / last.sum())))
        return out, gamma

    # ---- speculative program getters: one compiled program per
    # (kind, γ rung, sampling mode, top_k) via DecodeKey.extra — the
    # rung set is small and each entry compiles once, so steady state
    # swaps between compiled programs with ZERO retraces (the bench's
    # retrace ledger asserts it)

    def _spec_program(self, kind: str, extra: Tuple, builder,
                      draft: bool):
        from .program_cache import DecodeKey, decode_program_cache
        memo = (kind,) + tuple(extra)
        fn = self._spec_fns.get(memo)
        if fn is None:
            pool = self._draft_pool if draft else self.pool
            key = DecodeKey(
                kind=kind,
                model_sig=self._draft_sig if draft else self._model_sig,
                batch_bucket=1,
                page_budget=(pool.num_pages, pool.page_size,
                             pool.max_pages_per_seq),
                dtype=str(pool.k_pages[0].dtype),
                flags=self._flags.as_tuple(),
                extra=tuple(extra) + ((key_vocab.TAG_KV, self.kv_dtype),
                                      (key_vocab.TAG_WT,
                                       self.weight_dtype)))
            fn = decode_program_cache().get(key, builder)
            self._spec_fns[memo] = fn
            self._spec_keys[memo] = key
        if kind == "spec_draft":
            self.spec_draft_key = self._spec_keys[memo]
        elif kind == "spec_verify":
            self.spec_verify_key = self._spec_keys[memo]
        return fn

    def _spec_sync_program(self):
        """The DRAFT model's chunked-prefill program — the same r12
        builder the target's chunk path uses, keyed on the draft's
        signature and the sync chunk width."""
        return self._spec_program(
            "prefill_chunk", (self.spec_sync_chunk,),
            functools.partial(_build_chunk_prefill,
                              model=self.draft_model), draft=True)  # keycheck: disable=KEY002 — the documented model-object closure (draft model_sig rides the key)

    def _spec_draft_program(self, gamma: int, sample: bool,
                            top_k: int):
        fspec = self._fused_spec(draft=True)
        mode = ((key_vocab.ATOM_SAMPLE, int(top_k)) if sample
                else (key_vocab.ATOM_GREEDY,))
        path = ((key_vocab.ATOM_FUSED,) if fspec
                else (key_vocab.ATOM_GENERIC,))
        return self._spec_program(
            "spec_draft", (gamma,) + path + mode,
            functools.partial(_build_spec_draft, model=self.draft_model,  # keycheck: disable=KEY002 — the documented model-object closure (draft model_sig rides the key)
                              gamma=gamma, sample=sample,
                              top_k=int(top_k), fspec=fspec,
                              snap=self._flags if fspec else None),
            draft=True)

    def _spec_verify_program(self, gamma: int, sample: bool,
                             top_k: int):
        mode = ((key_vocab.ATOM_SAMPLE, int(top_k)) if sample
                else (key_vocab.ATOM_GREEDY,))
        return self._spec_program(
            "spec_verify", (gamma + 1,) + mode,
            functools.partial(_build_spec_verify, model=self.model,  # keycheck: disable=KEY002 — the documented model-object closure (model_sig rides the key)
                              sample=sample, top_k=int(top_k)),
            draft=False)

    def _step_inner(self) -> None:  # tracecheck: hotpath
        self._sweep_deadlines()
        self._probe_memo.clear()    # prefix probes are per-step
        # decode-ready requests present BEFORE this step's scheduler +
        # prefill work: the population that work below is stalling
        waiting = any(r is not None and r.prefill_pos is None
                      for r in self._slots)
        t_sched = time.perf_counter()
        # the step's admission order, sorted once and shared by the
        # migration demand estimate and the slot-fill loop below
        order = self._admission_order() if self._queue else []
        self._maybe_migrate(order)
        # SLO preemption runs BEFORE the slot fill: an unseated victim's
        # slot admits the endangered head in this very step
        self._preempt_for(order)
        # the step's ONE prefill-compute unit alternates between new
        # monolithic admissions and in-flight chunks under contention:
        # admissions always winning would starve a mid-prefill long
        # prompt forever under a stream of short arrivals; chunks are
        # finite per request, and a unit-needing head stops admission
        # (head-of-line), so neither side starves
        chunk_pending = any(r is not None and r.prefill_pos is not None
                            for r in self._slots)
        did_prefill = False
        chunk_ran_first = False
        if chunk_pending and self._chunk_turn:
            chunk_ran_first = self._chunk_step()
            did_prefill = chunk_ran_first
        for slot in range(self.bucket):
            if self._slots[slot] is not None or not order:
                continue
            req = self._next_admission(order)
            if req is None:
                break       # head page-blocked: wait, keep order
            if did_prefill and self._needs_prefill_unit(req):
                # the unit is spent: a monolithic-prefill head admits
                # next step (head-of-line — nothing jumps it); cursor-
                # only admissions behind a served head keep filling
                break
            order.remove(req)
            self._queue.remove(req)
            try:
                did_prefill |= self._admit(req, slot)
            except Exception as e:
                if isinstance(e, RuntimeError) and \
                        "page pool exhausted" in str(e):
                    # allocate came up short mid-step (pinned pages
                    # under-counted by the pre-check): back off to
                    # the queue instead of killing the step
                    self._rollback_admission(req, slot)
                    self._queue.insert(0, req)
                    self._observe_page_pressure(max(
                        1, self._pages_needed(req)
                        - self.pool.free_page_count()))
                    break
                # dispatch failure: hand the request to recovery
                # (it holds no slot state after the rollback)
                self._rollback_admission(req, slot)
                self._failed_admission = req
                raise
            if not self._head_blocked:
                # a BYPASS admission must not clear the pressure the
                # still-blocked head just published
                self._observe_page_pressure(0)
        # ONE prefill-compute unit per step (one monolithic prefill OR
        # one chunk — admitting several prefills back to back would
        # stack their stalls on every decoding request; the load bench
        # measured admission bursts, not long prompts, as the worst
        # stall): if admission spent it, chunks wait for their turn
        admission_used_unit = did_prefill and not chunk_ran_first
        if not did_prefill:
            did_prefill = self._chunk_step()
        # fairness flip: when chunks were pending but an admission took
        # the unit, the next contended step is the chunks'
        self._chunk_turn = chunk_pending and admission_used_unit
        if waiting and did_prefill:
            self._observe_stall(time.perf_counter() - t_sched)

        decode_rows = [r for r in self._slots
                       if r is not None and r.prefill_pos is None]
        self._observe_step_begin(len(decode_rows))
        if not decode_rows:
            return

        if self._draft_pool is not None and self._spec_step(decode_rows):
            # the rows were served by speculation rounds (draft scan +
            # verify chunk per row); the batched decode must not run
            # again this step
            self._observe_step_end()
            return

        b = self.bucket
        fn = self._decode_program(b)
        bt = jnp.asarray(self.pool.block_tables[:b])
        sl = jnp.asarray(self.pool.seq_lens[:b])
        t0 = time.perf_counter() if self._m.enabled else 0.0
        pools = self.pool.take_pools()
        self._f_decode.check()
        if self._stacked is not None:
            # N-layer program signature: the stacked per-group weight
            # structs ride as traced args (never baked constants)
            toks, states = fn(
                self._params, self._buffers,
                jnp.asarray(self._last_tok[:b, None]),
                pools, bt, sl, self._stacked)
        else:
            toks, states = fn(
                self._params, self._buffers,
                jnp.asarray(self._last_tok[:b, None]),
                pools, bt, sl)
        self._store(states)
        # the scheduler's designed sync point: admission/eviction need
        # the concrete token ids  # tracecheck: disable=TRC002
        toks = np.asarray(toks)

        now = time.perf_counter() if self._m.enabled else 0.0
        # one retroactive timeline event per step (cheaper than a span
        # object on the hot path; under a jax capture the compiled step
        # shows up natively)  # tracecheck: disable=TRC007
        self._m.event("engine.decode_step", t0, now,
                      active=len(decode_rows))
        if self.tp_degree > 1:
            # sharded dispatch envelope: compute + the per-layer psum
            # pair, observed host-side OUTSIDE the shard_map body
            # (meshcheck MSH006 keeps telemetry off the traced path)
            self._observe_collective(now - t0)
        for slot, req in enumerate(self._slots):
            if req is None:
                continue            # idle row wrote the null page; ignore
            if req.prefill_pos is not None:
                # mid-chunk-prefill slot: its decode row computed (and
                # wrote) garbage at the cursor position — the next chunk
                # overwrites that position and the cursor never advanced
                continue
            if req.temperature > 0.0 and not req.pending:
                # a sampled request never takes a token from the greedy
                # batch step — the spec verify program is its sampler.
                # The row's KV write at the cursor was a correct (and
                # repeatable) prefix write, but the cursor must NOT
                # advance: the next speculation round re-feeds this
                # position through its verify chunk
                continue
            self.pool.seq_lens[slot] += 1
            if req.pending:
                # still teacher-forcing the prompt suffix (prefix-cache
                # admission): the model output is a prompt-position logit,
                # not a generated token — feed the next suffix token
                self._last_tok[slot] = req.pending.pop(0)
                continue
            tok = int(toks[slot])
            if self._prefix is not None and not req.tokens:
                # first generated token of a shared admission: the whole
                # prompt's KV is now written — register the suffix's full
                # pages so repeats of THIS prompt deepen the cache too
                self._prefix.register(req.prompt,
                                      self.pool.block_tables[slot])
            if req.tokens:
                # per-token host-side latency write, bench-gated <2%
                # tracecheck: disable=TRC007
                self._m.itl.observe(now - req.t_last)
            else:
                # first token of a shared admission: TTFT closes here
                # tracecheck: disable=TRC007
                self._m.ttft.observe(now - req.t_submit)
            req.t_last = now
            req.tokens.append(tok)
            self._emit(req, tok)
            self._last_tok[slot] = tok
            self._finish_if_done(req)
        self._observe_step_end()

    # ------------------------------------------------- telemetry helpers
    # NOT hotpath-marked: plain host bookkeeping called once per step()
    # (the per-token writes stay inline above under pragma'd lines).

    def _observe_step_begin(self, n_active: int) -> None:
        m = self._m
        if not m.enabled:
            return
        if n_active:
            m.decode_steps.inc()
        else:
            # idle step: nothing decoded, but keep the gauges honest
            self._observe_step_end()

    def _observe_step_end(self) -> None:
        """One gauge refresh per step, AFTER finishes freed their
        slots/pages (and unpinned prefix pages), so a drained engine
        reads 0 everywhere instead of freezing at shortfall-time or
        pre-free values."""
        m = self._m
        if not m.enabled:
            return
        m.queue_depth.set(len(self._queue))
        m.occupancy.set(self.max_batch - self._slots.count(None))
        if not self._queue:
            m.page_pressure.set(0)      # an empty queue has no pressure
        self._observe_pool_ledger()

    def _observe_pool_ledger(self) -> None:
        """memwatch pool ledger (r13): the PagedKVCache ledger as
        step-end gauges plus one Perfetto counter sample, so memory
        watermarks line up with the serving timeline. All O(1) reads;
        fragmentation (a numpy sort over the free list) recomputes only
        when the free-list epoch moved — steady-state decode steps
        never touch the list and pay nothing for it."""
        m = self._m
        led = self.pool.ledger(fragmentation=False)
        pinned = (self._prefix.pinned_page_count()
                  if self._prefix is not None else 0)
        # the r09 gauges read the same pool state: set them from the
        # ledger rather than recomputing (serving pools always reserve
        # the null page, so pages_in_use == num_pages - 1 - free)
        m.kv_pages_in_use.set(led["pages_in_use"])
        if self._prefix is not None:
            m.prefix_pinned.set(pinned)
        m.pool_pages["used"].set(led["pages_in_use"])
        m.pool_pages["free"].set(led["pages_free"])
        m.pool_pages["shared"].set(led["pages_shared"])
        m.pool_pages["pinned"].set(pinned)
        m.pool_pages["spilled"].set(led["pages_spilled"])
        m.pool_bytes["used"].set(led["bytes_in_use"])
        m.pool_bytes["free"].set(led["bytes_free"])
        m.pool_bytes["shared"].set(
            led["pages_shared"] * led["bytes_per_page"])
        m.pool_bytes["pinned"].set(pinned * led["bytes_per_page"])
        m.pool_bytes["spilled"].set(led["bytes_spilled"])
        if led["pages_spilled"] > self._host_tier_peak:
            # tier watermark: the host-RAM bytes memwatch prices
            self._host_tier_peak = led["pages_spilled"]
            m.host_tier_peak.set(self._host_tier_peak)
        if led["epoch"] != self._pool_frag_epoch:
            self._pool_frag_epoch = led["epoch"]
            self._pool_frag = self.pool.free_list_fragmentation()
            m.pool_frag.set(self._pool_frag)
        m.counter_track(
            "kv_pool", time.perf_counter(),
            pages_in_use=led["pages_in_use"],
            bytes_in_use=led["bytes_in_use"],
            pages_shared=led["pages_shared"], pages_pinned=pinned,
            pages_spilled=led["pages_spilled"])

    def _observe_page_pressure(self, short: int) -> None:
        """Admission is (or stopped being) page-blocked: publish how
        many pages short the queue head is."""
        if self._m.enabled:
            self._m.page_pressure.set(short)

    def _observe_timeouts(self, n: int) -> None:
        if self._m.enabled:
            self._m.requests_timeout.inc(n)

    def _observe_recovery(self, n_replayed: int, n_failed: int,
                          dt: float) -> None:
        """One replay-recovery event: how many requests were re-queued,
        how many were terminated FAILED, and the recovery wall clock."""
        m = self._m
        if not m.enabled:
            return
        m.recoveries.inc()
        if n_replayed:
            m.retries.inc(n_replayed)
        if n_failed:
            m.requests_failed.inc(n_failed)
        m.recovery_seconds.observe(dt)
        # the ledger must reflect the FRESH pool immediately (the step
        # that died never reached its step-end refresh)
        self._observe_pool_ledger()

    def _observe_evict_shortfall(self, short: int) -> None:
        """``evict()`` freed fewer pages than the admission asked for:
        record how many, and the pinned-page pressure that explains it."""
        m = self._m
        if not m.enabled or self._prefix is None:
            return
        m.evict_short.inc(short)
        m.prefix_pinned.set(self._prefix.pinned_page_count())

    def _observe_preemption(self, req: Request) -> None:
        """One victim unseated for a tighter deadline: count it and the
        decode tokens its replay will regenerate."""
        m = self._m
        if not m.enabled:
            return
        m.preemptions.inc()
        if req.tokens:
            m.preempted_tokens.inc(len(req.tokens))

    def _observe_spec(self, gamma: int, accepted: int, rate: float,
                      t0: float, t1: float) -> None:
        """One speculation round retired: the accept-rate histogram
        (the adaptive-γ signal), accepted/rejected token counters, the
        γ gauge and a timeline event."""
        m = self._m
        if not m.enabled:
            return
        m.spec_rounds_c.inc()
        m.spec_accept.observe(rate)
        if accepted:
            m.spec_accepted.inc(accepted)
        if gamma - accepted:
            m.spec_rejected.inc(gamma - accepted)
        m.spec_gamma.set(gamma)
        m.event("engine.spec_round", t0, t1, gamma=gamma,
                accepted=accepted)

    def _observe_chunk(self, dt: float, final: bool = False) -> None:
        """One chunked-prefill dispatch retired: bank its wall clock —
        the unit a long-prompt arrival can stall decode by. The final
        chunk also closes the per-request prefill counter."""
        if self._m.enabled:
            self._m.prefill_chunk_s.observe(dt)
            if final:
                self._m.prefills.inc()

    def _observe_collective(self, dt: float) -> None:
        """One tensor-parallel decode dispatch retired: bank the wall
        clock of the sharded envelope (per-layer psum pair + compute).
        Host-side only — the shard_map body itself never writes
        telemetry (MSH006); a tp=1 engine never reaches here."""
        if self._m.enabled:
            self._m.collective_s.observe(dt)

    def _observe_stall(self, dt: float) -> None:
        """Scheduler + prefill work ran this step while decode-ready
        requests waited: that wall clock is the decode stall. The host
        probe (``max_decode_stall``) updates regardless of telemetry —
        the load bench asserts its bound."""
        if dt > self.max_decode_stall:
            self.max_decode_stall = dt
        if self._m.enabled:
            self._m.decode_stall_s.observe(dt)

    def _observe_bucket(self, migrated: bool = False) -> None:
        """The bucket gauge only moves on migration (plus once at
        construction), so it refreshes there instead of per step."""
        if self._m.enabled:
            self._m.bucket.set(self.bucket)
            if migrated:
                self._m.migrations.inc()


def _val(x):
    return x._value if hasattr(x, "_value") else x


# ------------------------------------------------------ program builders
# Module-level (not engine methods) so the decode program cache can hand
# one compiled step to every engine over the same model. All three donate
# ONLY the pools (each buffer appears once there; bt/sl are shared by
# every layer's state and must not be donated): page writes then alias
# the pool memory in place instead of copying every pool every token.

def _build_prefill(note_trace, model):
    from ..jit import functional_call

    def run(params, buffers, ids, pools, bt, sl):
        note_trace()
        states = [PagedDecodeState(k, v, bt, sl) for k, v in pools]
        logits, states = functional_call(
            model, params, ids, states, jnp.int32(0),
            buffers=buffers, method="forward_with_cache")
        return (jnp.argmax(logits[0, -1].astype(jnp.float32)), states)

    return jax.jit(run, donate_argnums=(3,))


def _build_chunk_prefill(note_trace, model):
    """The chunked-prefill step: one fixed-size b=1 chunk of prompt
    through the model against the PAGED pool. ``PagedChunkState`` routes
    attention onto the cache-READING prefill path — the chunk writes its
    KV at positions ``sl .. sl+C-1`` and attends to the already-written
    prefix plus itself causally — and ``sl[0]`` is the rotary/positional
    offset, so ONE compiled program serves every chunk of every prompt
    (the final partial chunk pads; pad rows are causally invisible to
    real rows and ``last_idx`` picks the real tail's logits). The argmax
    return is meaningful only on the final chunk — earlier dispatches
    never pull it, so they stay async."""
    from ..jit import functional_call
    from ..kernels.paged_attention import PagedChunkState

    def run(params, buffers, ids, pools, bt, sl, last_idx):
        note_trace()
        states = [PagedChunkState(k, v, bt, sl) for k, v in pools]
        logits, states = functional_call(
            model, params, ids, states, sl[0],
            buffers=buffers, method="forward_with_cache")
        return (jnp.argmax(logits[0, last_idx].astype(jnp.float32)),
                states)

    return jax.jit(run, donate_argnums=(3,))


def _build_generic_decode(note_trace, model):
    """The unfused decode step: one functional_call through the model's
    forward_with_cache (every layer an op chain XLA schedules)."""
    from ..jit import functional_call

    def run(params, buffers, toks, pools, bt, sl):
        note_trace()
        states = [PagedDecodeState(k, v, bt, sl) for k, v in pools]
        # offset=None -> per-slot positions from states.seq_lens
        logits, states = functional_call(
            model, params, toks, states, None,
            buffers=buffers, method="forward_with_cache")
        return (jnp.argmax(logits[:, -1].astype(jnp.float32), axis=-1),
                states)

    return jax.jit(run, donate_argnums=(3,))


def _spec_filtered_probs(rows, temperature, top_k, top_p):
    """The sampling law as a distribution: temperature scale, static
    top-k, traced top-p nucleus, softmax — the same filter chain
    generation's offline sampler applies, so the engine's rejection
    sampler and ``model.generate(do_sample=True)`` share one law.
    ``rows`` is (..., V) f32 logits; ``top_k`` is static (part of the
    program key), temperature/top_p are traced scalars."""
    from . import _top_k_filter, _top_p_filter
    lg = rows / jnp.maximum(temperature, jnp.float32(1e-6))
    if top_k and top_k > 0:
        lg = _top_k_filter(lg, top_k)
    lg = _top_p_filter(lg, top_p)
    return jax.nn.softmax(lg, axis=-1)


def _build_spec_draft(note_trace, model, gamma, sample, top_k,
                      fspec=None, snap=None):
    """The draft-propose program: γ draft forwards in ONE dispatch — a
    ``lax.scan`` over the draft's paged decode step with the scanned
    seq_lens advancing per iteration, so a speculation round costs two
    dispatches total (this + the verify chunk) instead of γ+1. The
    scan deliberately runs γ+1 iterations: the extra forward writes
    the last proposal's KV, so a fully-accepted round leaves the draft
    cache gap-free and the next round needs no catch-up sync (its
    output is discarded — only the first γ proposals return). In
    sample mode each iteration draws from the FILTERED draft
    distribution and the program also returns the γ q-rows the
    rejection test divides by. With ``fspec`` (the draft qualifies for
    the fused path) each scanned forward runs the per-layer fused
    block-decode kernel instead of the generic functional_call — the
    same fusion the batched decode step uses."""
    from ..jit import functional_call
    if fspec is not None:
        from ..kernels.fused_block_decode import (BlockDecodeWeights,
                                                  _rms,
                                                  fused_block_decode)
        nh, nkv = fspec["num_heads"], fspec["num_kv_heads"]
        theta, eps = fspec["rope_theta"], fspec["epsilon"]

    def run(params, buffers, tok, pools, bt, sl, *rest):
        note_trace()
        if sample:
            key, temperature, top_p = rest
        else:
            key = jnp.zeros((2,), jnp.uint32)

        def one(carry, _x):
            t, cpools, csl, k = carry
            if fspec is not None:
                allp = {**buffers, **params}
                x = jnp.take(allp[fspec["embed"]], t[:, 0], axis=0)
                nxt_pools = []
                for i, lw in enumerate(fspec["layers"]):
                    w = BlockDecodeWeights(
                        **{f: allp[n] for f, n in lw.items()})
                    kp, vp = cpools[i]
                    x, kp, vp = fused_block_decode(
                        x, w, kp, vp, bt, csl, num_heads=nh,
                        num_kv_heads=nkv, rope_theta=theta,
                        epsilon=eps, snap=snap)
                    nxt_pools.append((kp, vp))
                x = _rms(x, allp[fspec["final_norm"]], eps)
                if fspec["lm_head"]:
                    logits = x @ allp[fspec["lm_head"]]
                else:                           # tied embeddings
                    logits = x @ allp[fspec["embed"]].T
                row = logits[0].astype(jnp.float32)
            else:
                states = [PagedDecodeState(kp, vp, bt, csl)
                          for kp, vp in cpools]
                logits, states = functional_call(
                    model, params, t, states, None,
                    buffers=buffers, method="forward_with_cache")
                row = _val(logits)[0, -1].astype(jnp.float32)
                nxt_pools = [(_val(st.k_pages), _val(st.v_pages))
                             for st in states]
            if sample:
                k, sub = jax.random.split(k)
                q = _spec_filtered_probs(row, temperature, top_k, top_p)
                nxt = jax.random.categorical(
                    sub, jnp.log(q + 1e-30)).astype(jnp.int32)
                out = (nxt, q)
            else:
                nxt = jnp.argmax(row).astype(jnp.int32)
                out = nxt
            return (nxt[None, None], nxt_pools, csl + 1, k), out

        init = (tok, [(k, v) for k, v in pools], sl, key)
        (_, out_pools, _, _), outs = jax.lax.scan(
            one, init, None, length=gamma + 1)
        states = [PagedDecodeState(k, v, bt, sl)
                  for k, v in out_pools]
        if sample:
            props, qrows = outs
            return props[:gamma], qrows[:gamma], states
        return outs[:gamma], states

    return jax.jit(run, donate_argnums=(3,))


def _build_spec_verify(note_trace, model, sample, top_k):
    """The verify program IS a (1, γ+1) chunk of the r12 chunked-
    prefill machinery: ``PagedChunkState`` statically routes the S>1
    paged attention through the cache-reading path, the chunk writes
    the proposal positions' KV at ``sl .. sl+γ`` (so accepted tokens
    are already cached when the cursor rolls forward), and the
    returned per-position argmax (greedy) or filtered distributions
    (sample) drive host-side acceptance. No bespoke kernel — see
    KERNEL_DECISIONS round 16."""
    from ..jit import functional_call
    from ..kernels.paged_attention import PagedChunkState

    def run(params, buffers, ids, pools, bt, sl, *rest):
        note_trace()
        states = [PagedChunkState(k, v, bt, sl) for k, v in pools]
        logits, states = functional_call(
            model, params, ids, states, sl[0],
            buffers=buffers, method="forward_with_cache")
        rows = _val(logits)[0].astype(jnp.float32)      # (γ+1, V)
        greedy = jnp.argmax(rows, axis=-1).astype(jnp.int32)
        if not sample:
            return greedy, states
        temperature, top_p = rest
        return (greedy,
                _spec_filtered_probs(rows, temperature, top_k, top_p),
                states)

    return jax.jit(run, donate_argnums=(3,))


def _build_fused_decode(note_trace, spec, snap):
    """The fused decode step: embedding lookup, then ONE fused block
    kernel per layer (kernels/fused_block_decode.py — activations stay
    VMEM-resident across the block), final norm + lm head. Pure function
    of the param/buffer dicts — no model closure, so any same-config
    model shares the compiled program."""
    from ..kernels.fused_block_decode import (BlockDecodeWeights, _rms,
                                              fused_block_decode)

    nh, nkv = spec["num_heads"], spec["num_kv_heads"]
    theta, eps = spec["rope_theta"], spec["epsilon"]

    def run(params, buffers, toks, pools, bt, sl):
        note_trace()
        allp = {**buffers, **params}
        x = jnp.take(allp[spec["embed"]], toks[:, 0], axis=0)   # (B, H)
        states = []
        for i, lw in enumerate(spec["layers"]):
            w = BlockDecodeWeights(**{f: allp[n] for f, n in lw.items()})
            kp, vp = pools[i]
            x, kp, vp = fused_block_decode(
                x, w, kp, vp, bt, sl, num_heads=nh, num_kv_heads=nkv,
                rope_theta=theta, epsilon=eps, snap=snap)
            states.append(PagedDecodeState(kp, vp, bt, sl))
        x = _rms(x, allp[spec["final_norm"]], eps)
        if spec["lm_head"]:
            logits = x @ allp[spec["lm_head"]]
        else:                                   # tied embeddings
            logits = x @ allp[spec["embed"]].T
        return jnp.argmax(logits.astype(jnp.float32), axis=-1), states

    return jax.jit(run, donate_argnums=(3,))


def _build_fused_nlayer_decode(note_trace, spec, snap):
    """The N-layer fused decode step (FLAGS_fused_block_layers > 1):
    embedding lookup, then ONE multi-layer fused kernel per LAYER GROUP
    — activations stay VMEM-resident across all N blocks of a group and
    the per-layer weights stream through VMEM double-buffers inside a
    single pallas_call. ``stacked`` is the engine-built tuple of
    per-group MultiBlockDecodeWeights (one per spec["layer_groups"]
    entry, traced args so any same-config model shares the program —
    riding LAST so ``pools`` keeps the decode-step convention of
    position 3, the one donated slot every builder shares)."""
    from ..kernels.fused_block_decode import (_rms,
                                              fused_multi_block_decode)

    nh, nkv = spec["num_heads"], spec["num_kv_heads"]
    theta, eps = spec["rope_theta"], spec["epsilon"]
    groups = spec["layer_groups"]

    def run(params, buffers, toks, pools, bt, sl, stacked):
        note_trace()
        allp = {**buffers, **params}
        x = jnp.take(allp[spec["embed"]], toks[:, 0], axis=0)   # (B, H)
        states = []
        for gi, group in enumerate(groups):
            kps = [pools[i][0] for i in group]
            vps = [pools[i][1] for i in group]
            x, kps, vps = fused_multi_block_decode(
                x, stacked[gi], kps, vps, bt, sl, num_heads=nh,
                num_kv_heads=nkv, rope_theta=theta, epsilon=eps,
                snap=snap)
            states.extend(PagedDecodeState(kp, vp, bt, sl)
                          for kp, vp in zip(kps, vps))
        x = _rms(x, allp[spec["final_norm"]], eps)
        if spec["lm_head"]:
            logits = x @ allp[spec["lm_head"]]
        else:                                   # tied embeddings
            logits = x @ allp[spec["embed"]].T
        return jnp.argmax(logits.astype(jnp.float32), axis=-1), states

    return jax.jit(run, donate_argnums=(3,))


def _build_fused_nlayer_decode_tp(note_trace, spec, snap, mesh, axis, tp):
    """Tensor-parallel fused decode step (r19): the layer-group chain
    runs under ``shard_map`` over the mp axis — stacked weights
    column/row-sharded in the ``shard_block_weights`` layout, pools
    kv-head-sharded — while embedding lookup, the final norm and the lm
    head stay on the replicated residual outside the manual region.
    Exactly two collectives per layer (the row-parallel exits of wo and
    wd) through ``mp_ops._mp_allreduce``; the body holds NO telemetry
    and no host work (meshcheck MSH006/MSH001-clean). Same call
    signature and donation slot as the tp=1 N-layer builder, so the
    dispatch site does not fork."""
    from jax.sharding import PartitionSpec
    from ..kernels.fused_block_decode import (MultiBlockDecodeWeights,
                                              _rms,
                                              fused_multi_block_decode_tp)

    nh, nkv = spec["num_heads"], spec["num_kv_heads"]
    theta, eps = spec["rope_theta"], spec["epsilon"]
    groups = spec["layer_groups"]
    nh_s, nkv_s = nh // tp, nkv // tp
    rep = PartitionSpec()
    pool_spec = PartitionSpec(axis, None, None, None)
    w_spec = MultiBlockDecodeWeights(
        ln1=rep,
        wqkv=PartitionSpec(None, None, axis),
        wo=PartitionSpec(None, axis, None),
        ln2=rep,
        wgu=PartitionSpec(None, None, axis),
        wd=PartitionSpec(None, axis, None))

    def tp_block_chain(x, pools, bt, sl, stacked):
        # per-shard body: local head counts, local weight shards, local
        # kv-head pool partition; the residual x stays replicated
        out_pools = list(pools)
        for gi, group in enumerate(groups):
            kps = [pools[i][0] for i in group]
            vps = [pools[i][1] for i in group]
            x, kps, vps = fused_multi_block_decode_tp(
                x, stacked[gi], kps, vps, bt, sl, num_heads=nh_s,
                num_kv_heads=nkv_s, rope_theta=theta, epsilon=eps,
                axis_name=axis)
            for j, i in enumerate(group):
                out_pools[i] = (kps[j], vps[j])
        return x, out_pools

    sharded = jax.shard_map(
        tp_block_chain, mesh=mesh,
        in_specs=(rep, pool_spec, rep, rep,
                  tuple(w_spec for _ in groups)),
        out_specs=(rep, pool_spec),
        check_vma=False)

    def run(params, buffers, toks, pools, bt, sl, stacked):
        note_trace()
        allp = {**buffers, **params}
        x = jnp.take(allp[spec["embed"]], toks[:, 0], axis=0)   # (B, H)
        x, out_pools = sharded(x, list(pools), bt, sl, stacked)
        states = [PagedDecodeState(kp, vp, bt, sl)
                  for kp, vp in out_pools]
        x = _rms(x, allp[spec["final_norm"]], eps)
        if spec["lm_head"]:
            logits = x @ allp[spec["lm_head"]]
        else:                                   # tied embeddings
            logits = x @ allp[spec["embed"]].T
        return jnp.argmax(logits.astype(jnp.float32), axis=-1), states

    return jax.jit(run, donate_argnums=(3,))
