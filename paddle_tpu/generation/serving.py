"""Continuous-batching serving engine over the paged KV cache.

Reference parity target: the reference ecosystem's block-attention
serving runtime (PaddleNLP llm serving over block_multihead_attention /
the vLLM scheduler design): requests ADMIT into free batch slots the
moment one opens, every decode step runs the whole fixed-shape batch with
per-slot ragged lengths, and finished sequences return their pages to the
shared pool for the next request.

TPU-native structure: exactly TWO compiled programs serve steady state —
a b=1 prefill per distinct prompt length (bucketable) and ONE fixed-shape
decode step over max_batch slots. Ragged per-slot positions ride the
paged kernel's seq_lens; idle slots write into the reserved null page and
their outputs are ignored. The host loop between tokens is where the
scheduler lives — admission, eviction, and result collection are plain
Python on block tables.

Greedy decoding (the deterministic serving mode); sampling composes the
same way via the logits hook.
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from .. import observability as obs
from ..kernels.paged_attention import PagedDecodeState, PagedKVCache

__all__ = ["ServingEngine", "Request"]


@dataclass
class Request:
    rid: int
    prompt: np.ndarray                  # (P,) int32
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    tokens: List[int] = field(default_factory=list)
    slot: Optional[int] = None
    # prompt-suffix tokens still to be teacher-forced through the decode
    # step (prefix-cache admission skipped their prefill)
    pending: List[int] = field(default_factory=list)
    # prefix-cache pages this request adopted (pinned until it finishes)
    pinned: List[int] = field(default_factory=list)
    # telemetry lifecycle stamps (perf_counter): submit time and the
    # last generated-token time (inter-token latency baseline)
    t_submit: float = 0.0
    t_last: float = 0.0


class _EngineTelemetry:
    """Pre-bound instrument handles for the serving hot path: resolved
    once per engine, one attribute read per write inside ``step()`` —
    no registry lookups, no flag reads per token."""

    enabled = True

    def __init__(self):
        r = obs.registry()
        t = obs.tracer()
        self.span = t.span
        self.event = t.event
        self.submitted = r.counter(
            "serving_requests_submitted", "requests accepted by submit()")
        self.finished = r.counter(
            "serving_requests_finished", "requests that completed")
        self.prefills = r.counter(
            "serving_prefills", "b=1 prefill programs dispatched")
        self.shared_admits = r.counter(
            "serving_shared_admissions",
            "admissions that adopted cached prefix pages (prefill skipped)")
        self.decode_steps = r.counter(
            "serving_decode_steps", "full-batch decode steps dispatched")
        self.ttft = r.histogram(
            "serving_ttft_seconds",
            "time to first generated token, submit() to host-visible")
        self.itl = r.histogram(
            "serving_inter_token_seconds",
            "per-request latency between consecutive generated tokens")
        self.queue_depth = r.gauge(
            "serving_queue_depth", "requests waiting for a batch slot")
        self.occupancy = r.gauge(
            "serving_batch_occupancy",
            "active slots in the fixed-shape decode batch")
        self.kv_pages_in_use = r.gauge(
            "serving_kv_pages_in_use",
            "KV pool pages held by sequences or the prefix cache "
            "(excludes the reserved null page)")
        self.prefix_pinned = r.gauge(
            "serving_prefix_pinned_pages",
            "prefix-cache pages pinned by in-flight requests — the "
            "pressure that caps evict() reclaim")
        self.evict_short = r.counter(
            "serving_prefix_evict_shortfall_pages",
            "pages evict() was asked for but could not free "
            "(pinned/shared)")


class _NullEngineTelemetry:
    """FLAGS_telemetry=0 binding: every write is a no-op method call."""

    enabled = False

    def __init__(self):
        self.span = obs.null_span
        self.event = obs.null_event
        self.submitted = self.finished = self.prefills = obs.NULL
        self.shared_admits = self.decode_steps = obs.NULL
        self.ttft = self.itl = obs.NULL
        self.queue_depth = self.occupancy = obs.NULL
        self.kv_pages_in_use = self.prefix_pinned = obs.NULL
        self.evict_short = obs.NULL


class _PrefixTelemetry:
    enabled = True

    def __init__(self):
        r = obs.registry()
        self.hits = r.counter(
            "prefix_cache_hits", "lookups that matched >= 1 cached page")
        self.misses = r.counter(
            "prefix_cache_misses", "lookups that matched nothing")
        self.hit_pages = r.counter(
            "prefix_cache_hit_pages", "cached pages returned by lookups")
        self.registered_pages = r.counter(
            "prefix_cache_registered_pages",
            "new prompt pages registered into the trie")
        self.evicted_pages = r.counter(
            "prefix_cache_evicted_pages",
            "pages actually returned to the free list by evict()")


class _NullPrefixTelemetry:
    enabled = False

    def __init__(self):
        self.hits = self.misses = self.hit_pages = obs.NULL
        self.registered_pages = self.evicted_pages = obs.NULL


class PrefixCache:
    """Page-aligned prompt-prefix trie over a :class:`PagedKVCache`
    (reference parity target: the vLLM-style automatic prefix caching in
    the reference's serving ecosystem).

    Each node maps one FULL page of prompt tokens (keyed by its parent
    chain, so equal chunks under different prefixes never collide) to the
    page id holding that chunk's KV. Registered pages carry a cache
    reference, so they survive their creating request and later requests
    with the same prefix adopt them read-only instead of re-running
    prefill. Causality makes this sound: KV at position i depends only on
    tokens 0..i, so equal page-aligned prefixes have bitwise-equal pages.
    Eviction drops least-recently-used LEAF nodes only (an interior node
    must outlive its children or their chains become unreachable)."""

    _ROOT = ("root",)

    def __init__(self, pool: PagedKVCache):
        self.pool = pool
        self.page_size = pool.page_size
        # key -> {"page": int, "parent": key, "children": int, "tick": int,
        #         "pins": int}
        self._nodes: Dict[tuple, dict] = {}
        self._by_page: Dict[int, tuple] = {}    # page id -> node key
        self._tick = 0
        self._pinned_nodes = 0      # nodes with pins > 0 (O(1) gauge)
        self._m = (_PrefixTelemetry() if obs.enabled()
                   else _NullPrefixTelemetry())

    def _chunks(self, prompt: np.ndarray):
        key = self._ROOT
        for i in range(0, (len(prompt) // self.page_size) * self.page_size,
                       self.page_size):
            chunk = prompt[i:i + self.page_size].tobytes()
            key = (key, chunk)
            yield key

    def lookup(self, prompt: np.ndarray):
        """Longest cached page-aligned prefix: (page_ids, n_tokens)."""
        self._tick += 1
        pages: List[int] = []
        for key in self._chunks(prompt):
            node = self._nodes.get(key)
            if node is None:
                break
            node["tick"] = self._tick
            pages.append(node["page"])
        if pages:
            self._m.hits.inc()
            self._m.hit_pages.inc(len(pages))
        else:
            self._m.misses.inc()
        return pages, len(pages) * self.page_size

    def register(self, prompt: np.ndarray, block_row) -> None:
        """Pin the full prompt pages of a just-prefilled sequence."""
        self._tick += 1
        for i, key in enumerate(self._chunks(prompt)):
            node = self._nodes.get(key)
            if node is not None:        # dedup: keep the existing page
                node["tick"] = self._tick
                continue
            parent = key[0] if key[0] in self._nodes else None
            self._nodes[key] = {"page": int(block_row[i]), "parent": parent,
                                "children": 0, "tick": self._tick,
                                "pins": 0}
            self._by_page[int(block_row[i])] = key
            if parent is not None:
                self._nodes[parent]["children"] += 1
            self.pool.ref_page(int(block_row[i]))
            self._m.registered_pages.inc()

    def pin(self, pages) -> None:
        """Mark cached pages as adopted by an in-flight request: a pinned
        node is untouchable by ``evict`` until ``unpin``, independent of
        what the pool's reference counts happen to say. Call on
        adoption; ``unpin`` when the adopting request finishes."""
        for pid in pages:
            key = self._by_page.get(int(pid))
            if key is not None:
                node = self._nodes[key]
                node["pins"] += 1
                if node["pins"] == 1:
                    self._pinned_nodes += 1

    def unpin(self, pages) -> None:
        for pid in pages:
            key = self._by_page.get(int(pid))
            if key is not None and self._nodes[key]["pins"] > 0:
                node = self._nodes[key]
                node["pins"] -= 1
                if node["pins"] == 0:
                    self._pinned_nodes -= 1

    def evict(self, n_pages: int) -> int:
        """Free up to ``n_pages`` pages by dropping LRU leaf nodes,
        REFUSING any node that is pinned by an in-flight request's block
        table (pin count from adoption) or whose page anyone besides the
        cache still references (rc > 1). Returns the number of pages
        actually returned to the free list — callers size retry loops on
        real capacity, so unrefs that free nothing don't count."""
        freed = 0
        while freed < n_pages:
            leaves = [(node["tick"], key) for key, node in
                      self._nodes.items()
                      if node["children"] == 0 and node["pins"] == 0
                      and self.pool._page_rc[node["page"]] == 1]
            if not leaves:
                break
            _, key = min(leaves)
            node = self._nodes.pop(key)
            self._by_page.pop(node["page"], None)
            if node["parent"] is not None:
                self._nodes[node["parent"]]["children"] -= 1
            if self.pool.unref_page(node["page"]):
                freed += 1
        if freed:
            self._m.evicted_pages.inc(freed)
        return freed

    def pinned_page_count(self) -> int:
        """Pages untouchable by ``evict`` because an in-flight request's
        block table still points at them — the pinned-page pressure a
        shortfalling evict() reports instead of silently under-freeing.
        O(1): maintained on pin/unpin transitions (evict only ever drops
        pins==0 nodes), so the per-step gauge refresh costs nothing."""
        return self._pinned_nodes


class ServingEngine:
    """Drive ``model`` (a GenerationMixin Layer) as a continuous-batching
    server. ``submit`` enqueues; each ``step`` admits waiting requests
    into free slots and decodes one token for every active slot;
    ``run`` steps until drained and returns {rid: tokens}."""

    def __init__(self, model, max_batch: int = 4, page_size: int = 64,
                 num_pages: Optional[int] = None, max_seq_len: int = 1024,
                 prefix_cache: bool = False):
        from ..jit import ensure_live

        self.model = model
        self.max_batch = max_batch
        self.max_seq_len = max_seq_len
        spec = model.cache_spec()
        if num_pages is None:
            num_pages = 1 + max_batch * (-(-max_seq_len // page_size))
        params, buffers = model.raw_state()
        ensure_live(params, "call step.sync_to_model() first.")
        self._params, self._buffers = params, buffers
        dtype = jnp.result_type(next(iter(params.values())))
        self.pool = PagedKVCache(
            num_layers=len(spec), num_pages=num_pages, page_size=page_size,
            num_kv_heads=spec[0][0], head_dim=spec[0][1],
            max_batch=max_batch, max_seq_len=max_seq_len, dtype=dtype,
            reserve_null_page=True)
        maxpos = getattr(getattr(model, "config", None),
                         "max_position_embeddings", None)
        if maxpos is not None and max_seq_len > maxpos:
            raise ValueError(
                f"engine max_seq_len ({max_seq_len}) exceeds the model's "
                f"max_position_embeddings ({maxpos})")
        self._slots: List[Optional[Request]] = [None] * max_batch
        self._queue: List[Request] = []
        self._results: Dict[int, List[int]] = {}
        self._last_tok = np.zeros((max_batch,), np.int32)
        self._next_rid = 0
        self._prefill_fn = None
        self._decode_fn = None
        self.decode_key = None      # set on first decode (test probe)
        self._prefix = PrefixCache(self.pool) if prefix_cache else None
        # flag resolution happens ONCE per engine; the PROGRAM_FLAGS
        # snapshot (every flag a traced program can read — kernel
        # dispatch, flash blocks, compact stats, matmul precision) is
        # part of the program-cache key, so engines built under
        # different flag settings compile and cache distinct steps
        # instead of silently serving a program compiled under stale
        # flags, while eager-only flags (log_level, benchmark) never
        # force a spurious recompile
        from .. import flags as _flags
        from .program_cache import model_signature
        self._flags = _flags.snapshot(_flags.PROGRAM_FLAGS)
        self._model_sig = model_signature(model)
        # telemetry binding is per-engine and resolved once here (the
        # no-op stubs cost one method call per write when disabled)
        self._m = (_EngineTelemetry() if obs.enabled()
                   else _NullEngineTelemetry())

    # ------------------------------------------------------------ frontend
    def submit(self, prompt, max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None) -> int:
        prompt = np.asarray(
            prompt._value if hasattr(prompt, "_value") else prompt,
            np.int32).reshape(-1)
        if len(prompt) + max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds engine max_seq_len "
                f"({self.max_seq_len})")
        # a request that can never fit would deadlock FIFO admission
        need = -(-(len(prompt) + max_new_tokens) // self.pool.page_size)
        usable = self.pool.num_pages - 1        # null page reserved
        if need > min(usable, self.pool.max_pages_per_seq):
            raise ValueError(
                f"request needs {need} pages but the pool can ever offer "
                f"{min(usable, self.pool.max_pages_per_seq)}")
        rid = self._next_rid
        self._next_rid += 1
        req = Request(rid, prompt, int(max_new_tokens), eos_token_id)
        req.t_submit = time.perf_counter()
        self._queue.append(req)
        self._m.submitted.inc()
        return rid

    def has_work(self) -> bool:
        return bool(self._queue) or any(s is not None for s in self._slots)

    def run(self) -> Dict[int, List[int]]:
        while self.has_work():
            self.step()
        out, self._results = self._results, {}
        return out

    # ------------------------------------------------- compiled programs
    def _key(self, kind: str):
        from .program_cache import DecodeKey
        return DecodeKey(
            kind=kind, model_sig=self._model_sig,
            batch_bucket=self.max_batch,
            page_budget=(self.pool.num_pages, self.pool.page_size,
                         self.pool.max_pages_per_seq),
            dtype=str(self.pool.k_pages[0].dtype),
            flags=self._flags.as_tuple())

    def _fused_spec(self):
        """The model's fused-block layout when the fused path applies:
        FLAGS_fused_block_decode on, the model publishes
        ``block_decode_spec()``, and every named weight is live in the
        param/buffer dicts (a weight-quantized model restructures its
        Linears into int8 buffers and falls back to the generic step)."""
        if not self._flags.fused_block_decode:
            return None
        get_spec = getattr(self.model, "block_decode_spec", None)
        if get_spec is None:
            return None
        spec = get_spec()
        if spec is None:
            return None
        allp = {**self._buffers, **self._params}
        names = [spec["embed"], spec["final_norm"]]
        if spec["lm_head"]:
            names.append(spec["lm_head"])
        for lw in spec["layers"]:
            names.extend(lw.values())
        if not all(allp.get(n) is not None for n in names):
            return None
        return spec

    def _prefill_program(self):
        if self._prefill_fn is None:
            from .program_cache import decode_program_cache
            self._prefill_fn = decode_program_cache().get(
                self._key("prefill"),
                functools.partial(_build_prefill, model=self.model))
        return self._prefill_fn

    def _decode_program(self):
        if self._decode_fn is None:
            from .program_cache import decode_program_cache
            spec = self._fused_spec()
            key = self._key("decode_fused" if spec else "decode_generic")
            if spec:
                builder = functools.partial(_build_fused_decode, spec=spec,
                                            snap=self._flags)
            else:
                builder = functools.partial(_build_generic_decode,
                                            model=self.model)
            self._decode_fn = decode_program_cache().get(key, builder)
            self.decode_key = key
        return self._decode_fn

    # ----------------------------------------------------------- internals
    # Donation discipline (tracecheck TRC003): the compiled programs
    # donate their pools argument, so the dispatch sites pass
    # ``self.pool.take_pools()`` — the cache's references are detached
    # BEFORE the buffers are invalidated by donation, and ``_store``
    # installs the step's returned pools.  A dispatch that raises leaves
    # the pool explicitly empty (take_pools refuses a second detach)
    # rather than silently aliasing deleted device buffers.

    def _store(self, states) -> None:
        self.pool.install_pools(
            [(_val(st.k_pages), _val(st.v_pages)) for st in states])

    def _admit_shared(self, req: Request, slot: int, pages: List[int],
                      n_cached: int) -> None:
        """Prefix-cache admission: adopt the cached prompt pages read-only
        and teacher-force the remaining suffix through the ordinary decode
        step (one token per engine step) — no new compiled program, and
        the cached portion's prefill compute is skipped entirely. The
        model output while suffix tokens are pending is a prompt-position
        logit and is discarded; the step that feeds the LAST suffix token
        emits the first generated token."""
        self.pool.adopt_shared(slot, pages)
        if self._prefix is not None:
            # pin count on adoption: evict() must never free pages an
            # in-flight request's block table still points at
            self._prefix.pin(pages)
            req.pinned = [int(p) for p in pages]
        self.pool.seq_lens[slot] = n_cached
        suffix = req.prompt[n_cached:]
        self.pool.allocate(slot, len(suffix) + req.max_new_tokens)
        self._last_tok[slot] = int(suffix[0])
        req.pending = [int(t) for t in suffix[1:]]
        req.slot = slot
        self._slots[slot] = req
        self._m.shared_admits.inc()

    def _prefill(self, req: Request, slot: int) -> None:
        # queued phase closes at admission: submit() -> here (once per
        # REQUEST, not per token)  # tracecheck: disable=TRC007
        self._m.event("request.queued", req.t_submit, time.perf_counter(),
                      rid=req.rid)
        if self._prefix is not None:
            pages, n_cached = self._prefix.lookup(req.prompt)
            # never cover the WHOLE prompt: the first generated token's
            # logits are not cached, so at least one prompt token must go
            # through compute
            while pages and n_cached >= len(req.prompt):
                pages = pages[:-1]
                n_cached -= self.pool.page_size
            # coverage threshold: the suffix replays one token per decode
            # step, so a barely-covered long prompt would trade one b=1
            # prefill for hundreds of full-batch steps — take the shared
            # path only when the replay is small (a couple of pages) or
            # the cached part dominates it
            suffix_len = len(req.prompt) - n_cached
            if pages and suffix_len <= max(2 * self.pool.page_size,
                                           n_cached):
                self._admit_shared(req, slot, pages, n_cached)
                return

        p = len(req.prompt)
        # the cached prefill program: jit itself caches one compilation
        # per prompt length (bucket/pad prompts in production to bound
        # that set); the program-cache layer shares those compilations
        # across engine instances over the same model
        fn = self._prefill_program()

        self.pool.allocate(slot, p + req.max_new_tokens)
        bt = jnp.asarray(self.pool.block_tables[slot:slot + 1])
        # per-request prefill timeline span  # tracecheck: disable=TRC007
        with self._m.span("request.prefill", rid=req.rid, prompt_len=p):
            tok, states = fn(self._params, self._buffers,
                             jnp.asarray(req.prompt[None]),
                             self.pool.take_pools(),
                             bt, jnp.zeros((1,), jnp.int32))
            # b=1 prefill wrote THROUGH slot's block table into the
            # shared pool arrays; adopt them and the slot's bookkeeping
            self._store(states)
            tok = int(tok)              # the span owns the token pull
        # once per admitted request  # tracecheck: disable=TRC007
        self._m.prefills.inc()
        self.pool.seq_lens[slot] = p
        self._last_tok[slot] = tok
        tnow = time.perf_counter()
        req.t_last = tnow
        # TTFT closes on the prefill's token  # tracecheck: disable=TRC007
        self._m.ttft.observe(tnow - req.t_submit)
        req.tokens.append(tok)
        req.slot = slot
        self._slots[slot] = req
        if self._prefix is not None:
            # pin this prompt's full pages for future shared admissions
            # (they are immutable: later writes land at seq_len and up)
            self._prefix.register(req.prompt, self.pool.block_tables[slot])
        self._finish_if_done(req)

    def _finish_if_done(self, req: Request) -> None:
        done = len(req.tokens) >= req.max_new_tokens or (
            req.eos_token_id is not None
            and req.tokens and req.tokens[-1] == req.eos_token_id)
        if done and req.slot is not None:
            self.pool.free_sequence(req.slot)
            if req.pinned and self._prefix is not None:
                self._prefix.unpin(req.pinned)
                req.pinned = []
            self._slots[req.slot] = None
            self._results[req.rid] = req.tokens
            req.slot = None
            # once per finished request  # tracecheck: disable=TRC007
            self._m.finished.inc()
            if self._m.enabled:
                # lifecycle close event  # tracecheck: disable=TRC007
                self._m.event("request.complete", req.t_submit,
                              time.perf_counter(), rid=req.rid,
                              tokens=len(req.tokens))

    def step(self) -> None:  # tracecheck: hotpath
        # admission: fill every free slot that has pages available
        for slot in range(self.max_batch):
            if self._slots[slot] is None and self._queue:
                req = self._queue[0]
                need = -(-(len(req.prompt) + req.max_new_tokens)
                         // self.pool.page_size)
                if need > self.pool.free_page_count() and self._prefix:
                    # cached-but-unshared pages are reclaimable capacity;
                    # a shortfall (pinned/shared pages refusing eviction)
                    # is banked as pressure, not silently swallowed
                    want = need - self.pool.free_page_count()
                    freed = self._prefix.evict(want)
                    if freed < want:
                        self._observe_evict_shortfall(want - freed)
                if need > self.pool.free_page_count():
                    break           # wait for pages (FIFO, no starvation)
                self._queue.pop(0)
                self._prefill(req, slot)

        active = [s for s in self._slots if s is not None]
        self._observe_step_begin(len(active))
        if not active:
            return

        fn = self._decode_program()
        bt = jnp.asarray(self.pool.block_tables[:self.max_batch])
        sl = jnp.asarray(self.pool.seq_lens[:self.max_batch])
        t0 = time.perf_counter() if self._m.enabled else 0.0
        toks, states = fn(
            self._params, self._buffers,
            jnp.asarray(self._last_tok[:, None]),
            self.pool.take_pools(), bt, sl)
        self._store(states)
        # the scheduler's designed sync point: admission/eviction need
        # the concrete token ids  # tracecheck: disable=TRC002
        toks = np.asarray(toks)

        now = time.perf_counter() if self._m.enabled else 0.0
        # one retroactive timeline event per step (cheaper than a span
        # object on the hot path; under a jax capture the compiled step
        # shows up natively)  # tracecheck: disable=TRC007
        self._m.event("engine.decode_step", t0, now, active=len(active))
        for slot, req in enumerate(self._slots):
            if req is None:
                continue            # idle row wrote the null page; ignore
            self.pool.seq_lens[slot] += 1
            if req.pending:
                # still teacher-forcing the prompt suffix (prefix-cache
                # admission): the model output is a prompt-position logit,
                # not a generated token — feed the next suffix token
                self._last_tok[slot] = req.pending.pop(0)
                continue
            tok = int(toks[slot])
            if self._prefix is not None and not req.tokens:
                # first generated token of a shared admission: the whole
                # prompt's KV is now written — register the suffix's full
                # pages so repeats of THIS prompt deepen the cache too
                self._prefix.register(req.prompt,
                                      self.pool.block_tables[slot])
            if req.tokens:
                # per-token host-side latency write, bench-gated <2%
                # tracecheck: disable=TRC007
                self._m.itl.observe(now - req.t_last)
            else:
                # first token of a shared admission: TTFT closes here
                # tracecheck: disable=TRC007
                self._m.ttft.observe(now - req.t_submit)
            req.t_last = now
            req.tokens.append(tok)
            self._last_tok[slot] = tok
            self._finish_if_done(req)
        self._observe_step_end()

    # ------------------------------------------------- telemetry helpers
    # NOT hotpath-marked: plain host bookkeeping called once per step()
    # (the per-token writes stay inline above under pragma'd lines).

    def _observe_step_begin(self, n_active: int) -> None:
        m = self._m
        if not m.enabled:
            return
        if n_active:
            m.decode_steps.inc()
        else:
            # idle step: nothing decoded, but keep the gauges honest
            self._observe_step_end()

    def _observe_step_end(self) -> None:
        """One gauge refresh per step, AFTER finishes freed their
        slots/pages (and unpinned prefix pages), so a drained engine
        reads 0 everywhere instead of freezing at shortfall-time or
        pre-free values."""
        m = self._m
        if not m.enabled:
            return
        m.queue_depth.set(len(self._queue))
        m.occupancy.set(self.max_batch - self._slots.count(None))
        m.kv_pages_in_use.set(
            self.pool.num_pages - 1 - self.pool.free_page_count())
        if self._prefix is not None:
            m.prefix_pinned.set(self._prefix.pinned_page_count())

    def _observe_evict_shortfall(self, short: int) -> None:
        """``evict()`` freed fewer pages than the admission asked for:
        record how many, and the pinned-page pressure that explains it."""
        m = self._m
        if not m.enabled or self._prefix is None:
            return
        m.evict_short.inc(short)
        m.prefix_pinned.set(self._prefix.pinned_page_count())


def _val(x):
    return x._value if hasattr(x, "_value") else x


# ------------------------------------------------------ program builders
# Module-level (not engine methods) so the decode program cache can hand
# one compiled step to every engine over the same model. All three donate
# ONLY the pools (each buffer appears once there; bt/sl are shared by
# every layer's state and must not be donated): page writes then alias
# the pool memory in place instead of copying every pool every token.

def _build_prefill(note_trace, model):
    from ..jit import functional_call

    def run(params, buffers, ids, pools, bt, sl):
        note_trace()
        states = [PagedDecodeState(k, v, bt, sl) for k, v in pools]
        logits, states = functional_call(
            model, params, ids, states, jnp.int32(0),
            buffers=buffers, method="forward_with_cache")
        return (jnp.argmax(logits[0, -1].astype(jnp.float32)), states)

    return jax.jit(run, donate_argnums=(3,))


def _build_generic_decode(note_trace, model):
    """The unfused decode step: one functional_call through the model's
    forward_with_cache (every layer an op chain XLA schedules)."""
    from ..jit import functional_call

    def run(params, buffers, toks, pools, bt, sl):
        note_trace()
        states = [PagedDecodeState(k, v, bt, sl) for k, v in pools]
        # offset=None -> per-slot positions from states.seq_lens
        logits, states = functional_call(
            model, params, toks, states, None,
            buffers=buffers, method="forward_with_cache")
        return (jnp.argmax(logits[:, -1].astype(jnp.float32), axis=-1),
                states)

    return jax.jit(run, donate_argnums=(3,))


def _build_fused_decode(note_trace, spec, snap):
    """The fused decode step: embedding lookup, then ONE fused block
    kernel per layer (kernels/fused_block_decode.py — activations stay
    VMEM-resident across the block), final norm + lm head. Pure function
    of the param/buffer dicts — no model closure, so any same-config
    model shares the compiled program."""
    from ..kernels.fused_block_decode import (BlockDecodeWeights, _rms,
                                              fused_block_decode)

    nh, nkv = spec["num_heads"], spec["num_kv_heads"]
    theta, eps = spec["rope_theta"], spec["epsilon"]

    def run(params, buffers, toks, pools, bt, sl):
        note_trace()
        allp = {**buffers, **params}
        x = jnp.take(allp[spec["embed"]], toks[:, 0], axis=0)   # (B, H)
        states = []
        for i, lw in enumerate(spec["layers"]):
            w = BlockDecodeWeights(**{f: allp[n] for f, n in lw.items()})
            kp, vp = pools[i]
            x, kp, vp = fused_block_decode(
                x, w, kp, vp, bt, sl, num_heads=nh, num_kv_heads=nkv,
                rope_theta=theta, epsilon=eps, snap=snap)
            states.append(PagedDecodeState(kp, vp, bt, sl))
        x = _rms(x, allp[spec["final_norm"]], eps)
        if spec["lm_head"]:
            logits = x @ allp[spec["lm_head"]]
        else:                                   # tied embeddings
            logits = x @ allp[spec["embed"]].T
        return jnp.argmax(logits.astype(jnp.float32), axis=-1), states

    return jax.jit(run, donate_argnums=(3,))
