"""Continuous-batching serving engine over the paged KV cache.

Reference parity target: the reference ecosystem's block-attention
serving runtime (PaddleNLP llm serving over block_multihead_attention /
the vLLM scheduler design): requests ADMIT into free batch slots the
moment one opens, every decode step runs the whole fixed-shape batch with
per-slot ragged lengths, and finished sequences return their pages to the
shared pool for the next request.

TPU-native structure: exactly TWO compiled programs serve steady state —
a b=1 prefill per distinct prompt length (bucketable) and ONE fixed-shape
decode step over max_batch slots. Ragged per-slot positions ride the
paged kernel's seq_lens; idle slots write into the reserved null page and
their outputs are ignored. The host loop between tokens is where the
scheduler lives — admission, eviction, and result collection are plain
Python on block tables.

Greedy decoding (the deterministic serving mode); sampling composes the
same way via the logits hook.
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from .. import observability as obs
from ..kernels.paged_attention import PagedDecodeState, PagedKVCache
from ..testing import faults

__all__ = ["ServingEngine", "Request"]

# terminal request statuses (Request.status / ServingEngine.status)
OK, FAILED, TIMEOUT = "OK", "FAILED", "TIMEOUT"


@dataclass
class Request:
    rid: int
    prompt: np.ndarray                  # (P,) int32
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    tokens: List[int] = field(default_factory=list)
    slot: Optional[int] = None
    # prompt-suffix tokens still to be teacher-forced through the decode
    # step (prefix-cache admission skipped their prefill)
    pending: List[int] = field(default_factory=list)
    # prefix-cache pages this request adopted (pinned until it finishes)
    pinned: List[int] = field(default_factory=list)
    # telemetry lifecycle stamps (perf_counter): submit time and the
    # last generated-token time (inter-token latency baseline)
    t_submit: float = 0.0
    t_last: float = 0.0
    # absolute perf_counter cutoff (submit(deadline=...)); enforced at
    # step boundaries — None = no deadline
    deadline: Optional[float] = None
    # terminal status ("PENDING" while queued/in flight)
    status: str = "PENDING"
    error: Optional[str] = None
    # replay-recovery bookkeeping: consecutive no-progress replays, and
    # the token count at the last failure (progress resets the budget)
    retries: int = 0
    progress_mark: int = -1


class _EngineTelemetry:
    """Pre-bound instrument handles for the serving hot path: resolved
    once per engine, one attribute read per write inside ``step()`` —
    no registry lookups, no flag reads per token."""

    enabled = True

    def __init__(self):
        r = obs.registry()
        t = obs.tracer()
        self.span = t.span
        self.event = t.event
        self.submitted = r.counter(
            "serving_requests_submitted", "requests accepted by submit()")
        self.finished = r.counter(
            "serving_requests_finished", "requests that completed")
        self.prefills = r.counter(
            "serving_prefills", "b=1 prefill programs dispatched")
        self.shared_admits = r.counter(
            "serving_shared_admissions",
            "admissions that adopted cached prefix pages (prefill skipped)")
        self.decode_steps = r.counter(
            "serving_decode_steps", "full-batch decode steps dispatched")
        self.ttft = r.histogram(
            "serving_ttft_seconds",
            "time to first generated token, submit() to host-visible")
        self.itl = r.histogram(
            "serving_inter_token_seconds",
            "per-request latency between consecutive generated tokens")
        self.queue_depth = r.gauge(
            "serving_queue_depth", "requests waiting for a batch slot")
        self.occupancy = r.gauge(
            "serving_batch_occupancy",
            "active slots in the fixed-shape decode batch")
        self.kv_pages_in_use = r.gauge(
            "serving_kv_pages_in_use",
            "KV pool pages held by sequences or the prefix cache "
            "(excludes the reserved null page)")
        self.prefix_pinned = r.gauge(
            "serving_prefix_pinned_pages",
            "prefix-cache pages pinned by in-flight requests — the "
            "pressure that caps evict() reclaim")
        self.evict_short = r.counter(
            "serving_prefix_evict_shortfall_pages",
            "pages evict() was asked for but could not free "
            "(pinned/shared)")
        # ---- fault-tolerance instruments (replay recovery, r10)
        self.retries = r.counter(
            "serving_retries_total",
            "in-flight request replays re-queued by recovery after a "
            "failed dispatch")
        self.recoveries = r.counter(
            "serving_recoveries",
            "replay-recovery events: failed dispatch -> fresh pools + "
            "re-queue of all in-flight requests")
        self.requests_failed = r.counter(
            "serving_requests_failed",
            "requests terminated FAILED (no-progress retry budget "
            "exhausted)")
        self.requests_timeout = r.counter(
            "serving_requests_timeout",
            "requests terminated TIMEOUT (per-request deadline or the "
            "run(max_wall=...) watchdog)")
        self.recovery_seconds = r.histogram(
            "serving_recovery_seconds",
            "wall clock of one replay recovery (fresh pools + requeue, "
            "excluding backoff sleep)")
        self.page_pressure = r.gauge(
            "serving_page_pressure",
            "KV pages short at the last page-blocked admission (0 = "
            "admission is not page-blocked)")


class _NullEngineTelemetry:
    """FLAGS_telemetry=0 binding: every write is a no-op method call."""

    enabled = False

    def __init__(self):
        self.span = obs.null_span
        self.event = obs.null_event
        self.submitted = self.finished = self.prefills = obs.NULL
        self.shared_admits = self.decode_steps = obs.NULL
        self.ttft = self.itl = obs.NULL
        self.queue_depth = self.occupancy = obs.NULL
        self.kv_pages_in_use = self.prefix_pinned = obs.NULL
        self.evict_short = obs.NULL
        self.retries = self.recoveries = obs.NULL
        self.requests_failed = self.requests_timeout = obs.NULL
        self.recovery_seconds = self.page_pressure = obs.NULL


class _PrefixTelemetry:
    enabled = True

    def __init__(self):
        r = obs.registry()
        self.hits = r.counter(
            "prefix_cache_hits", "lookups that matched >= 1 cached page")
        self.misses = r.counter(
            "prefix_cache_misses", "lookups that matched nothing")
        self.hit_pages = r.counter(
            "prefix_cache_hit_pages", "cached pages returned by lookups")
        self.registered_pages = r.counter(
            "prefix_cache_registered_pages",
            "new prompt pages registered into the trie")
        self.evicted_pages = r.counter(
            "prefix_cache_evicted_pages",
            "pages actually returned to the free list by evict()")


class _NullPrefixTelemetry:
    enabled = False

    def __init__(self):
        self.hits = self.misses = self.hit_pages = obs.NULL
        self.registered_pages = self.evicted_pages = obs.NULL


class PrefixCache:
    """Page-aligned prompt-prefix trie over a :class:`PagedKVCache`
    (reference parity target: the vLLM-style automatic prefix caching in
    the reference's serving ecosystem).

    Each node maps one FULL page of prompt tokens (keyed by its parent
    chain, so equal chunks under different prefixes never collide) to the
    page id holding that chunk's KV. Registered pages carry a cache
    reference, so they survive their creating request and later requests
    with the same prefix adopt them read-only instead of re-running
    prefill. Causality makes this sound: KV at position i depends only on
    tokens 0..i, so equal page-aligned prefixes have bitwise-equal pages.
    Eviction drops least-recently-used LEAF nodes only (an interior node
    must outlive its children or their chains become unreachable)."""

    _ROOT = ("root",)

    def __init__(self, pool: PagedKVCache):
        self.pool = pool
        self.page_size = pool.page_size
        # key -> {"page": int, "parent": key, "children": int, "tick": int,
        #         "pins": int}
        self._nodes: Dict[tuple, dict] = {}
        self._by_page: Dict[int, tuple] = {}    # page id -> node key
        self._tick = 0
        self._pinned_nodes = 0      # nodes with pins > 0 (O(1) gauge)
        self._m = (_PrefixTelemetry() if obs.enabled()
                   else _NullPrefixTelemetry())

    def _chunks(self, prompt: np.ndarray):
        key = self._ROOT
        for i in range(0, (len(prompt) // self.page_size) * self.page_size,
                       self.page_size):
            chunk = prompt[i:i + self.page_size].tobytes()
            key = (key, chunk)
            yield key

    def lookup(self, prompt: np.ndarray):
        """Longest cached page-aligned prefix: (page_ids, n_tokens)."""
        self._tick += 1
        pages: List[int] = []
        for key in self._chunks(prompt):
            node = self._nodes.get(key)
            if node is None:
                break
            node["tick"] = self._tick
            pages.append(node["page"])
        if pages:
            self._m.hits.inc()
            self._m.hit_pages.inc(len(pages))
        else:
            self._m.misses.inc()
        return pages, len(pages) * self.page_size

    def register(self, prompt: np.ndarray, block_row) -> None:
        """Pin the full prompt pages of a just-prefilled sequence."""
        self._tick += 1
        for i, key in enumerate(self._chunks(prompt)):
            node = self._nodes.get(key)
            if node is not None:        # dedup: keep the existing page
                node["tick"] = self._tick
                continue
            parent = key[0] if key[0] in self._nodes else None
            self._nodes[key] = {"page": int(block_row[i]), "parent": parent,
                                "children": 0, "tick": self._tick,
                                "pins": 0}
            self._by_page[int(block_row[i])] = key
            if parent is not None:
                self._nodes[parent]["children"] += 1
            self.pool.ref_page(int(block_row[i]))
            self._m.registered_pages.inc()

    def pin(self, pages) -> None:
        """Mark cached pages as adopted by an in-flight request: a pinned
        node is untouchable by ``evict`` until ``unpin``, independent of
        what the pool's reference counts happen to say. Call on
        adoption; ``unpin`` when the adopting request finishes."""
        for pid in pages:
            key = self._by_page.get(int(pid))
            if key is not None:
                node = self._nodes[key]
                node["pins"] += 1
                if node["pins"] == 1:
                    self._pinned_nodes += 1

    def unpin(self, pages) -> None:
        for pid in pages:
            key = self._by_page.get(int(pid))
            if key is not None and self._nodes[key]["pins"] > 0:
                node = self._nodes[key]
                node["pins"] -= 1
                if node["pins"] == 0:
                    self._pinned_nodes -= 1

    def evict(self, n_pages: int) -> int:
        """Free up to ``n_pages`` pages by dropping LRU leaf nodes,
        REFUSING any node that is pinned by an in-flight request's block
        table (pin count from adoption) or whose page anyone besides the
        cache still references (rc > 1). Returns the number of pages
        actually returned to the free list — callers size retry loops on
        real capacity, so unrefs that free nothing don't count."""
        freed = 0
        while freed < n_pages:
            leaves = [(node["tick"], key) for key, node in
                      self._nodes.items()
                      if node["children"] == 0 and node["pins"] == 0
                      and self.pool._page_rc[node["page"]] == 1]
            if not leaves:
                break
            _, key = min(leaves)
            node = self._nodes.pop(key)
            self._by_page.pop(node["page"], None)
            if node["parent"] is not None:
                self._nodes[node["parent"]]["children"] -= 1
            if self.pool.unref_page(node["page"]):
                freed += 1
        if freed:
            self._m.evicted_pages.inc(freed)
        return freed

    def pinned_page_count(self) -> int:
        """Pages untouchable by ``evict`` because an in-flight request's
        block table still points at them — the pinned-page pressure a
        shortfalling evict() reports instead of silently under-freeing.
        O(1): maintained on pin/unpin transitions (evict only ever drops
        pins==0 nodes), so the per-step gauge refresh costs nothing."""
        return self._pinned_nodes


class ServingEngine:
    """Drive ``model`` (a GenerationMixin Layer) as a continuous-batching
    server. ``submit`` enqueues; each ``step`` admits waiting requests
    into free slots and decodes one token for every active slot;
    ``run`` steps until drained and returns {rid: tokens}."""

    def __init__(self, model, max_batch: int = 4, page_size: int = 64,
                 num_pages: Optional[int] = None, max_seq_len: int = 1024,
                 prefix_cache: bool = False):
        from ..jit import ensure_live

        self.model = model
        self.max_batch = max_batch
        self.max_seq_len = max_seq_len
        spec = model.cache_spec()
        if num_pages is None:
            num_pages = 1 + max_batch * (-(-max_seq_len // page_size))
        params, buffers = model.raw_state()
        ensure_live(params, "call step.sync_to_model() first.")
        self._params, self._buffers = params, buffers
        dtype = jnp.result_type(next(iter(params.values())))
        # pool geometry is kept so replay recovery can allocate FRESH
        # pools with the identical shape (same compiled programs apply)
        self._pool_geom = dict(
            num_layers=len(spec), num_pages=num_pages, page_size=page_size,
            num_kv_heads=spec[0][0], head_dim=spec[0][1],
            max_batch=max_batch, max_seq_len=max_seq_len, dtype=dtype,
            reserve_null_page=True)
        self.pool = PagedKVCache(**self._pool_geom)
        maxpos = getattr(getattr(model, "config", None),
                         "max_position_embeddings", None)
        if maxpos is not None and max_seq_len > maxpos:
            raise ValueError(
                f"engine max_seq_len ({max_seq_len}) exceeds the model's "
                f"max_position_embeddings ({maxpos})")
        self._slots: List[Optional[Request]] = [None] * max_batch
        self._queue: List[Request] = []
        self._results: Dict[int, List[int]] = {}
        self._status: Dict[int, str] = {}
        self._last_tok = np.zeros((max_batch,), np.int32)
        self._next_rid = 0
        self._prefill_fn = None
        self._decode_fn = None
        self.decode_key = None      # set on first decode (test probe)
        self._prefix_enabled = bool(prefix_cache)
        self._prefix = PrefixCache(self.pool) if prefix_cache else None
        # ---- fault tolerance: injection sites bind at construction
        # (NULL stubs when FLAGS_fault_inject is unset — zero hot-path
        # cost, the telemetry idiom) and the replay-recovery budget
        from .. import flags as _rflags
        self._f_prefill = faults.site("prefill")
        self._f_decode = faults.site("decode_dispatch")
        self.max_retries = int(_rflags.get_flag("serving_max_retries"))
        self.retry_backoff = float(
            _rflags.get_flag("serving_retry_backoff"))
        self._consec_failures = 0   # engine-wide no-progress failures
        self._failed_admission: Optional[Request] = None
        # flag resolution happens ONCE per engine; the PROGRAM_FLAGS
        # snapshot (every flag a traced program can read — kernel
        # dispatch, flash blocks, compact stats, matmul precision) is
        # part of the program-cache key, so engines built under
        # different flag settings compile and cache distinct steps
        # instead of silently serving a program compiled under stale
        # flags, while eager-only flags (log_level, benchmark) never
        # force a spurious recompile
        from .. import flags as _flags
        from .program_cache import model_signature
        self._flags = _flags.snapshot(_flags.PROGRAM_FLAGS)
        self._model_sig = model_signature(model)
        # telemetry binding is per-engine and resolved once here (the
        # no-op stubs cost one method call per write when disabled)
        self._m = (_EngineTelemetry() if obs.enabled()
                   else _NullEngineTelemetry())

    # ------------------------------------------------------------ frontend
    def submit(self, prompt, max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None,
               deadline: Optional[float] = None) -> int:
        """Enqueue one request. ``deadline`` (seconds from now) bounds
        its total latency: a request past its deadline — queued or in
        flight — is terminated ``TIMEOUT`` at the next step boundary
        with whatever tokens it produced."""
        prompt = np.asarray(
            prompt._value if hasattr(prompt, "_value") else prompt,
            np.int32).reshape(-1)
        if len(prompt) + max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds engine max_seq_len "
                f"({self.max_seq_len})")
        # a request that can never fit would deadlock FIFO admission
        need = -(-(len(prompt) + max_new_tokens) // self.pool.page_size)
        usable = self.pool.num_pages - 1        # null page reserved
        if need > min(usable, self.pool.max_pages_per_seq):
            raise ValueError(
                f"request needs {need} pages but the pool can ever offer "
                f"{min(usable, self.pool.max_pages_per_seq)}")
        rid = self._next_rid
        self._next_rid += 1
        req = Request(rid, prompt, int(max_new_tokens), eos_token_id)
        req.t_submit = time.perf_counter()
        if deadline is not None:
            req.deadline = req.t_submit + float(deadline)
        self._queue.append(req)
        self._m.submitted.inc()
        return rid

    def has_work(self) -> bool:
        return bool(self._queue) or any(s is not None for s in self._slots)

    def run(self, max_wall: Optional[float] = None) -> Dict[int, List[int]]:
        """Step until drained and return ``{rid: tokens}`` (partial
        tokens for FAILED/TIMEOUT requests — check :meth:`status`).
        ``max_wall`` is the watchdog: past it, everything still queued
        or in flight is terminated ``TIMEOUT`` and ``run`` returns
        instead of spinning on a wedged backend."""
        t0 = time.perf_counter()
        while self.has_work():
            if max_wall is not None and \
                    time.perf_counter() - t0 > max_wall:
                self._expire_all("run(max_wall=%.3f) watchdog" % max_wall)
                break
            self.step()
        out, self._results = self._results, {}
        # statuses are retained for exactly the requests this drain
        # returned: a long-lived engine must not accumulate one status
        # entry per request forever
        self._status = {rid: self._status[rid] for rid in out
                        if rid in self._status}
        return out

    def results(self) -> Dict[int, List[int]]:
        """Completed results accumulated so far, WITHOUT draining them —
        the exception-safety accessor: after a mid-``run`` raise, every
        request that finished before the failure is retrievable here
        (``run`` only hands over-and-clears on a clean drain)."""
        return {rid: list(toks) for rid, toks in self._results.items()}

    def status(self, rid: int) -> str:
        """Terminal status for ``rid``: ``OK`` / ``FAILED`` / ``TIMEOUT``
        (``PENDING`` while queued or in flight). Statuses survive until
        the NEXT completed ``run`` drain, then prune with its results."""
        return self._status.get(rid, "PENDING")

    def statuses(self) -> Dict[int, str]:
        return dict(self._status)

    # ------------------------------------------------- compiled programs
    def _key(self, kind: str):
        from .program_cache import DecodeKey
        return DecodeKey(
            kind=kind, model_sig=self._model_sig,
            batch_bucket=self.max_batch,
            page_budget=(self.pool.num_pages, self.pool.page_size,
                         self.pool.max_pages_per_seq),
            dtype=str(self.pool.k_pages[0].dtype),
            flags=self._flags.as_tuple())

    def _fused_spec(self):
        """The model's fused-block layout when the fused path applies:
        FLAGS_fused_block_decode on, the model publishes
        ``block_decode_spec()``, and every named weight is live in the
        param/buffer dicts (a weight-quantized model restructures its
        Linears into int8 buffers and falls back to the generic step)."""
        if not self._flags.fused_block_decode:
            return None
        get_spec = getattr(self.model, "block_decode_spec", None)
        if get_spec is None:
            return None
        spec = get_spec()
        if spec is None:
            return None
        allp = {**self._buffers, **self._params}
        names = [spec["embed"], spec["final_norm"]]
        if spec["lm_head"]:
            names.append(spec["lm_head"])
        for lw in spec["layers"]:
            names.extend(lw.values())
        if not all(allp.get(n) is not None for n in names):
            return None
        return spec

    def _prefill_program(self):
        if self._prefill_fn is None:
            from .program_cache import decode_program_cache
            self._prefill_fn = decode_program_cache().get(
                self._key("prefill"),
                functools.partial(_build_prefill, model=self.model))
        return self._prefill_fn

    def _decode_program(self):
        if self._decode_fn is None:
            from .program_cache import decode_program_cache
            spec = self._fused_spec()
            key = self._key("decode_fused" if spec else "decode_generic")
            if spec:
                builder = functools.partial(_build_fused_decode, spec=spec,
                                            snap=self._flags)
            else:
                builder = functools.partial(_build_generic_decode,
                                            model=self.model)
            self._decode_fn = decode_program_cache().get(key, builder)
            self.decode_key = key
        return self._decode_fn

    # ----------------------------------------------------------- internals
    # Donation discipline (tracecheck TRC003): the compiled programs
    # donate their pools argument, so the dispatch sites pass
    # ``self.pool.take_pools()`` — the cache's references are detached
    # BEFORE the buffers are invalidated by donation, and ``_store``
    # installs the step's returned pools.  A dispatch that raises leaves
    # the pool explicitly empty (take_pools refuses a second detach)
    # rather than silently aliasing deleted device buffers.

    def _store(self, states) -> None:
        self.pool.install_pools(
            [(_val(st.k_pages), _val(st.v_pages)) for st in states])

    def _admit_shared(self, req: Request, slot: int, pages: List[int],
                      n_cached: int) -> None:
        """Prefix-cache admission: adopt the cached prompt pages read-only
        and teacher-force the remaining suffix through the ordinary decode
        step (one token per engine step) — no new compiled program, and
        the cached portion's prefill compute is skipped entirely. The
        model output while suffix tokens are pending is a prompt-position
        logit and is discarded; the step that feeds the LAST suffix token
        emits the first generated token."""
        self.pool.adopt_shared(slot, pages)
        if self._prefix is not None:
            # pin count on adoption: evict() must never free pages an
            # in-flight request's block table still points at
            self._prefix.pin(pages)
            req.pinned = [int(p) for p in pages]
        self.pool.seq_lens[slot] = n_cached
        suffix = req.prompt[n_cached:]
        self.pool.allocate(slot, len(suffix) + req.max_new_tokens)
        self._last_tok[slot] = int(suffix[0])
        req.pending = [int(t) for t in suffix[1:]]
        req.slot = slot
        self._slots[slot] = req
        self._m.shared_admits.inc()

    def _admission_feed(self, req: Request) -> np.ndarray:
        """What prefill teacher-forces for this admission. First
        admission: the prompt. Replay admission (recovery re-queued an
        in-flight request): prompt + every already-emitted token — all
        host-side state — so the b=1 prefill reconstructs the KV cache
        and its argmax IS the next greedy token. Greedy decoding makes
        the replayed continuation identical to the uninterrupted one."""
        if not req.tokens:
            return req.prompt
        return np.concatenate(
            [req.prompt, np.asarray(req.tokens, np.int32)])

    def _prefill(self, req: Request, slot: int) -> None:
        # queued phase closes at admission: submit() -> here (once per
        # REQUEST, not per token)  # tracecheck: disable=TRC007
        self._m.event("request.queued", req.t_submit, time.perf_counter(),
                      rid=req.rid)
        replay = bool(req.tokens)
        if self._prefix is not None and not replay:
            pages, n_cached = self._prefix.lookup(req.prompt)
            # never cover the WHOLE prompt: the first generated token's
            # logits are not cached, so at least one prompt token must go
            # through compute
            while pages and n_cached >= len(req.prompt):
                pages = pages[:-1]
                n_cached -= self.pool.page_size
            # coverage threshold: the suffix replays one token per decode
            # step, so a barely-covered long prompt would trade one b=1
            # prefill for hundreds of full-batch steps — take the shared
            # path only when the replay is small (a couple of pages) or
            # the cached part dominates it
            suffix_len = len(req.prompt) - n_cached
            if pages and suffix_len <= max(2 * self.pool.page_size,
                                           n_cached):
                self._admit_shared(req, slot, pages, n_cached)
                return

        feed = self._admission_feed(req)
        p = len(feed)
        # the cached prefill program: jit itself caches one compilation
        # per prompt length (bucket/pad prompts in production to bound
        # that set); the program-cache layer shares those compilations
        # across engine instances over the same model
        fn = self._prefill_program()

        remaining = req.max_new_tokens - len(req.tokens)
        self.pool.allocate(slot, p + remaining)
        bt = jnp.asarray(self.pool.block_tables[slot:slot + 1])
        # per-request prefill timeline span  # tracecheck: disable=TRC007
        with self._m.span("request.prefill", rid=req.rid, prompt_len=p):
            pools = self.pool.take_pools()
            self._f_prefill.check()
            tok, states = fn(self._params, self._buffers,
                             jnp.asarray(feed[None]),
                             pools, bt, jnp.zeros((1,), jnp.int32))
            # b=1 prefill wrote THROUGH slot's block table into the
            # shared pool arrays; adopt them and the slot's bookkeeping
            self._store(states)
            tok = int(tok)              # the span owns the token pull
        # once per admitted request  # tracecheck: disable=TRC007
        self._m.prefills.inc()
        self.pool.seq_lens[slot] = p
        self._last_tok[slot] = tok
        tnow = time.perf_counter()
        if replay:
            # the replayed prefill's token continues the sequence: its
            # latency is inter-token, not a second TTFT
            # tracecheck: disable=TRC007
            self._m.itl.observe(tnow - req.t_last)
        else:
            # TTFT closes on the prefill's token
            # tracecheck: disable=TRC007
            self._m.ttft.observe(tnow - req.t_submit)
        req.t_last = tnow
        req.tokens.append(tok)
        req.slot = slot
        self._slots[slot] = req
        if self._prefix is not None and not replay:
            # pin this prompt's full pages for future shared admissions
            # (they are immutable: later writes land at seq_len and up)
            self._prefix.register(req.prompt, self.pool.block_tables[slot])
        self._finish_if_done(req)

    def _finalize(self, req: Request, status: str,
                  error: Optional[str] = None) -> None:
        """Terminal bookkeeping shared by every way a request ends:
        release its slot/pages/pins, bank its tokens (partial for
        FAILED/TIMEOUT) and record the status. Pure host state — no
        telemetry here (callers observe through ``_observe_*``)."""
        if req.slot is not None:
            self.pool.free_sequence(req.slot)
            self._slots[req.slot] = None
            req.slot = None
        if req.pinned and self._prefix is not None:
            self._prefix.unpin(req.pinned)
        req.pinned = []
        req.pending = []
        req.status = status
        req.error = error
        self._results[req.rid] = req.tokens
        self._status[req.rid] = status

    def _finish_if_done(self, req: Request) -> None:
        done = len(req.tokens) >= req.max_new_tokens or (
            req.eos_token_id is not None
            and req.tokens and req.tokens[-1] == req.eos_token_id)
        if done and req.slot is not None:
            self._finalize(req, OK)
            # once per finished request  # tracecheck: disable=TRC007
            self._m.finished.inc()
            if self._m.enabled:
                # lifecycle close event  # tracecheck: disable=TRC007
                self._m.event("request.complete", req.t_submit,
                              time.perf_counter(), rid=req.rid,
                              tokens=len(req.tokens))

    def _sweep_deadlines(self) -> None:
        """Step-boundary deadline enforcement: terminate every queued or
        in-flight request past its ``submit(deadline=...)`` cutoff with
        status TIMEOUT and its partial tokens banked."""
        now = time.perf_counter()
        expired = [r for r in self._slots
                   if r is not None and r.deadline is not None
                   and now > r.deadline]
        expired += [r for r in self._queue
                    if r.deadline is not None and now > r.deadline]
        if not expired:
            return
        rids = {r.rid for r in expired}
        self._queue = [r for r in self._queue if r.rid not in rids]
        for req in expired:
            self._finalize(req, TIMEOUT, "deadline exceeded")
        self._observe_timeouts(len(expired))

    def _expire_all(self, why: str) -> None:
        """The ``run(max_wall=...)`` watchdog tripped: terminate every
        remaining request TIMEOUT instead of spinning forever."""
        remaining = [r for r in self._slots if r is not None]
        remaining += list(self._queue)
        self._queue = []
        for req in remaining:
            self._finalize(req, TIMEOUT, why)
        if remaining:
            self._observe_timeouts(len(remaining))
        self._observe_step_end()

    def step(self) -> None:  # tracecheck: hotpath
        """One scheduler round: deadline sweep, admission, one decode
        dispatch. A failed dispatch does NOT propagate — replay recovery
        (fresh pools, re-queue of all in-flight requests, bounded
        retries with exponential backoff) runs instead, and requests
        only ever end in a terminal OK/FAILED/TIMEOUT status."""
        try:
            self._step_inner()
            self._consec_failures = 0
        except Exception as exc:
            self._recover_dispatch(exc)

    def _recover_dispatch(self, exc: Exception) -> None:
        """Replay recovery. The donated dispatch died, so the pool is
        already detached (r08 discipline) and its device buffers are
        unrecoverable — but every request's prompt AND emitted tokens
        are host-side state. Allocate fresh pools, terminate requests
        whose no-progress retry budget is exhausted, re-queue the rest
        for re-prefill from prompt + emitted tokens (greedy decoding
        makes the replayed continuation bit-identical), and back off
        exponentially while nothing progresses."""
        t0 = time.perf_counter()
        live = [r for r in self._slots if r is not None]
        failed_adm = self._failed_admission
        self._failed_admission = None
        # a failed admission was rolled back before the raise, so it is
        # never also in a slot
        victims = live + ([failed_adm] if failed_adm is not None else [])
        if not victims:
            # nothing was in flight: this is not a dispatch failure the
            # replay machinery can absorb — a bookkeeping error must
            # stay loud (results so far remain retrievable, see
            # ``results()``)
            raise exc
        self._rebuild_pool()
        survivors: List[Request] = []
        failed: List[Request] = []
        any_progress = False
        for req in victims:
            req.slot = None
            req.pending = []
            req.pinned = []     # pinned pages died with the old pool
            progress = len(req.tokens)
            if progress > req.progress_mark:
                any_progress = True
                req.retries = 1
            else:
                req.retries += 1
            req.progress_mark = progress
            if req.retries > self.max_retries:
                failed.append(req)
            else:
                survivors.append(req)
        self._slots = [None] * self.max_batch
        self._last_tok[:] = 0
        for req in failed:
            self._finalize(req, FAILED, repr(exc))
        # replays keep their submission order relative to the queue
        self._queue = sorted(survivors + self._queue,
                             key=lambda r: r.rid)
        self._consec_failures = (1 if any_progress
                                 else self._consec_failures + 1)
        self._observe_recovery(len(survivors), len(failed),
                               time.perf_counter() - t0)
        if self._queue:
            time.sleep(min(
                self.retry_backoff * (2 ** (self._consec_failures - 1)),
                2.0))

    def _rebuild_pool(self) -> None:
        """Fresh pools with the identical geometry, so the already-
        compiled prefill/decode programs (keyed on that geometry) serve
        the replays without a retrace. The prefix cache indexed pages of
        the dead pool and restarts empty."""
        self.pool = PagedKVCache(**self._pool_geom)
        self._prefix = (PrefixCache(self.pool)
                        if self._prefix_enabled else None)

    def _rollback_admission(self, req: Request, slot: int) -> None:
        """Undo a partial admission (page exhaustion mid-``allocate``):
        return the slot's pages, drop adopted pins, clear teacher-forced
        state — the request goes back to the queue head intact."""
        self.pool.free_sequence(slot)
        if req.pinned and self._prefix is not None:
            self._prefix.unpin(req.pinned)
        req.pinned = []
        req.pending = []
        req.slot = None
        self._slots[slot] = None

    def _step_inner(self) -> None:  # tracecheck: hotpath
        self._sweep_deadlines()
        # admission: fill every free slot that has pages available
        for slot in range(self.max_batch):
            if self._slots[slot] is None and self._queue:
                req = self._queue[0]
                need = -(-(len(req.prompt) + req.max_new_tokens)
                         // self.pool.page_size)
                if need > self.pool.free_page_count() and self._prefix:
                    # cached-but-unshared pages are reclaimable capacity;
                    # a shortfall (pinned/shared pages refusing eviction)
                    # is banked as pressure, not silently swallowed
                    want = need - self.pool.free_page_count()
                    freed = self._prefix.evict(want)
                    if freed < want:
                        self._observe_evict_shortfall(want - freed)
                if need > self.pool.free_page_count():
                    # graceful degradation: the request WAITS in the
                    # queue (FIFO, no starvation) and the shortfall is
                    # published as pressure, not an exception
                    self._observe_page_pressure(
                        need - self.pool.free_page_count())
                    break
                self._queue.pop(0)
                try:
                    self._prefill(req, slot)
                except Exception as e:
                    if isinstance(e, RuntimeError) and \
                            "page pool exhausted" in str(e):
                        # allocate came up short mid-step (pinned pages
                        # under-counted by the pre-check): back off to
                        # the queue instead of killing the step
                        self._rollback_admission(req, slot)
                        self._queue.insert(0, req)
                        self._observe_page_pressure(max(
                            1, need - self.pool.free_page_count()))
                        break
                    # dispatch failure: hand the request to recovery
                    # (it holds no slot state after the rollback)
                    self._rollback_admission(req, slot)
                    self._failed_admission = req
                    raise
                self._observe_page_pressure(0)

        active = [s for s in self._slots if s is not None]
        self._observe_step_begin(len(active))
        if not active:
            return

        fn = self._decode_program()
        bt = jnp.asarray(self.pool.block_tables[:self.max_batch])
        sl = jnp.asarray(self.pool.seq_lens[:self.max_batch])
        t0 = time.perf_counter() if self._m.enabled else 0.0
        pools = self.pool.take_pools()
        self._f_decode.check()
        toks, states = fn(
            self._params, self._buffers,
            jnp.asarray(self._last_tok[:, None]),
            pools, bt, sl)
        self._store(states)
        # the scheduler's designed sync point: admission/eviction need
        # the concrete token ids  # tracecheck: disable=TRC002
        toks = np.asarray(toks)

        now = time.perf_counter() if self._m.enabled else 0.0
        # one retroactive timeline event per step (cheaper than a span
        # object on the hot path; under a jax capture the compiled step
        # shows up natively)  # tracecheck: disable=TRC007
        self._m.event("engine.decode_step", t0, now, active=len(active))
        for slot, req in enumerate(self._slots):
            if req is None:
                continue            # idle row wrote the null page; ignore
            self.pool.seq_lens[slot] += 1
            if req.pending:
                # still teacher-forcing the prompt suffix (prefix-cache
                # admission): the model output is a prompt-position logit,
                # not a generated token — feed the next suffix token
                self._last_tok[slot] = req.pending.pop(0)
                continue
            tok = int(toks[slot])
            if self._prefix is not None and not req.tokens:
                # first generated token of a shared admission: the whole
                # prompt's KV is now written — register the suffix's full
                # pages so repeats of THIS prompt deepen the cache too
                self._prefix.register(req.prompt,
                                      self.pool.block_tables[slot])
            if req.tokens:
                # per-token host-side latency write, bench-gated <2%
                # tracecheck: disable=TRC007
                self._m.itl.observe(now - req.t_last)
            else:
                # first token of a shared admission: TTFT closes here
                # tracecheck: disable=TRC007
                self._m.ttft.observe(now - req.t_submit)
            req.t_last = now
            req.tokens.append(tok)
            self._last_tok[slot] = tok
            self._finish_if_done(req)
        self._observe_step_end()

    # ------------------------------------------------- telemetry helpers
    # NOT hotpath-marked: plain host bookkeeping called once per step()
    # (the per-token writes stay inline above under pragma'd lines).

    def _observe_step_begin(self, n_active: int) -> None:
        m = self._m
        if not m.enabled:
            return
        if n_active:
            m.decode_steps.inc()
        else:
            # idle step: nothing decoded, but keep the gauges honest
            self._observe_step_end()

    def _observe_step_end(self) -> None:
        """One gauge refresh per step, AFTER finishes freed their
        slots/pages (and unpinned prefix pages), so a drained engine
        reads 0 everywhere instead of freezing at shortfall-time or
        pre-free values."""
        m = self._m
        if not m.enabled:
            return
        m.queue_depth.set(len(self._queue))
        m.occupancy.set(self.max_batch - self._slots.count(None))
        m.kv_pages_in_use.set(
            self.pool.num_pages - 1 - self.pool.free_page_count())
        if not self._queue:
            m.page_pressure.set(0)      # an empty queue has no pressure
        if self._prefix is not None:
            m.prefix_pinned.set(self._prefix.pinned_page_count())

    def _observe_page_pressure(self, short: int) -> None:
        """Admission is (or stopped being) page-blocked: publish how
        many pages short the queue head is."""
        if self._m.enabled:
            self._m.page_pressure.set(short)

    def _observe_timeouts(self, n: int) -> None:
        if self._m.enabled:
            self._m.requests_timeout.inc(n)

    def _observe_recovery(self, n_replayed: int, n_failed: int,
                          dt: float) -> None:
        """One replay-recovery event: how many requests were re-queued,
        how many were terminated FAILED, and the recovery wall clock."""
        m = self._m
        if not m.enabled:
            return
        m.recoveries.inc()
        if n_replayed:
            m.retries.inc(n_replayed)
        if n_failed:
            m.requests_failed.inc(n_failed)
        m.recovery_seconds.observe(dt)

    def _observe_evict_shortfall(self, short: int) -> None:
        """``evict()`` freed fewer pages than the admission asked for:
        record how many, and the pinned-page pressure that explains it."""
        m = self._m
        if not m.enabled or self._prefix is None:
            return
        m.evict_short.inc(short)
        m.prefix_pinned.set(self._prefix.pinned_page_count())


def _val(x):
    return x._value if hasattr(x, "_value") else x


# ------------------------------------------------------ program builders
# Module-level (not engine methods) so the decode program cache can hand
# one compiled step to every engine over the same model. All three donate
# ONLY the pools (each buffer appears once there; bt/sl are shared by
# every layer's state and must not be donated): page writes then alias
# the pool memory in place instead of copying every pool every token.

def _build_prefill(note_trace, model):
    from ..jit import functional_call

    def run(params, buffers, ids, pools, bt, sl):
        note_trace()
        states = [PagedDecodeState(k, v, bt, sl) for k, v in pools]
        logits, states = functional_call(
            model, params, ids, states, jnp.int32(0),
            buffers=buffers, method="forward_with_cache")
        return (jnp.argmax(logits[0, -1].astype(jnp.float32)), states)

    return jax.jit(run, donate_argnums=(3,))


def _build_generic_decode(note_trace, model):
    """The unfused decode step: one functional_call through the model's
    forward_with_cache (every layer an op chain XLA schedules)."""
    from ..jit import functional_call

    def run(params, buffers, toks, pools, bt, sl):
        note_trace()
        states = [PagedDecodeState(k, v, bt, sl) for k, v in pools]
        # offset=None -> per-slot positions from states.seq_lens
        logits, states = functional_call(
            model, params, toks, states, None,
            buffers=buffers, method="forward_with_cache")
        return (jnp.argmax(logits[:, -1].astype(jnp.float32), axis=-1),
                states)

    return jax.jit(run, donate_argnums=(3,))


def _build_fused_decode(note_trace, spec, snap):
    """The fused decode step: embedding lookup, then ONE fused block
    kernel per layer (kernels/fused_block_decode.py — activations stay
    VMEM-resident across the block), final norm + lm head. Pure function
    of the param/buffer dicts — no model closure, so any same-config
    model shares the compiled program."""
    from ..kernels.fused_block_decode import (BlockDecodeWeights, _rms,
                                              fused_block_decode)

    nh, nkv = spec["num_heads"], spec["num_kv_heads"]
    theta, eps = spec["rope_theta"], spec["epsilon"]

    def run(params, buffers, toks, pools, bt, sl):
        note_trace()
        allp = {**buffers, **params}
        x = jnp.take(allp[spec["embed"]], toks[:, 0], axis=0)   # (B, H)
        states = []
        for i, lw in enumerate(spec["layers"]):
            w = BlockDecodeWeights(**{f: allp[n] for f, n in lw.items()})
            kp, vp = pools[i]
            x, kp, vp = fused_block_decode(
                x, w, kp, vp, bt, sl, num_heads=nh, num_kv_heads=nkv,
                rope_theta=theta, epsilon=eps, snap=snap)
            states.append(PagedDecodeState(kp, vp, bt, sl))
        x = _rms(x, allp[spec["final_norm"]], eps)
        if spec["lm_head"]:
            logits = x @ allp[spec["lm_head"]]
        else:                                   # tied embeddings
            logits = x @ allp[spec["embed"]].T
        return jnp.argmax(logits.astype(jnp.float32), axis=-1), states

    return jax.jit(run, donate_argnums=(3,))
