"""Continuous-batching serving engine over the paged KV cache.

Reference parity target: the reference ecosystem's block-attention
serving runtime (PaddleNLP llm serving over block_multihead_attention /
the vLLM scheduler design): requests ADMIT into free batch slots the
moment one opens, every decode step runs the whole fixed-shape batch with
per-slot ragged lengths, and finished sequences return their pages to the
shared pool for the next request.

TPU-native structure: exactly TWO compiled programs serve steady state —
a b=1 prefill per distinct prompt length (bucketable) and ONE fixed-shape
decode step over max_batch slots. Ragged per-slot positions ride the
paged kernel's seq_lens; idle slots write into the reserved null page and
their outputs are ignored. The host loop between tokens is where the
scheduler lives — admission, eviction, and result collection are plain
Python on block tables.

Greedy decoding (the deterministic serving mode); sampling composes the
same way via the logits hook.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..kernels.paged_attention import PagedDecodeState, PagedKVCache

__all__ = ["ServingEngine", "Request"]


@dataclass
class Request:
    rid: int
    prompt: np.ndarray                  # (P,) int32
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    tokens: List[int] = field(default_factory=list)
    slot: Optional[int] = None
    # prompt-suffix tokens still to be teacher-forced through the decode
    # step (prefix-cache admission skipped their prefill)
    pending: List[int] = field(default_factory=list)


class PrefixCache:
    """Page-aligned prompt-prefix trie over a :class:`PagedKVCache`
    (reference parity target: the vLLM-style automatic prefix caching in
    the reference's serving ecosystem).

    Each node maps one FULL page of prompt tokens (keyed by its parent
    chain, so equal chunks under different prefixes never collide) to the
    page id holding that chunk's KV. Registered pages carry a cache
    reference, so they survive their creating request and later requests
    with the same prefix adopt them read-only instead of re-running
    prefill. Causality makes this sound: KV at position i depends only on
    tokens 0..i, so equal page-aligned prefixes have bitwise-equal pages.
    Eviction drops least-recently-used LEAF nodes only (an interior node
    must outlive its children or their chains become unreachable)."""

    _ROOT = ("root",)

    def __init__(self, pool: PagedKVCache):
        self.pool = pool
        self.page_size = pool.page_size
        # key -> {"page": int, "parent": key, "children": int, "tick": int}
        self._nodes: Dict[tuple, dict] = {}
        self._tick = 0

    def _chunks(self, prompt: np.ndarray):
        key = self._ROOT
        for i in range(0, (len(prompt) // self.page_size) * self.page_size,
                       self.page_size):
            chunk = prompt[i:i + self.page_size].tobytes()
            key = (key, chunk)
            yield key

    def lookup(self, prompt: np.ndarray):
        """Longest cached page-aligned prefix: (page_ids, n_tokens)."""
        self._tick += 1
        pages: List[int] = []
        for key in self._chunks(prompt):
            node = self._nodes.get(key)
            if node is None:
                break
            node["tick"] = self._tick
            pages.append(node["page"])
        return pages, len(pages) * self.page_size

    def register(self, prompt: np.ndarray, block_row) -> None:
        """Pin the full prompt pages of a just-prefilled sequence."""
        self._tick += 1
        for i, key in enumerate(self._chunks(prompt)):
            node = self._nodes.get(key)
            if node is not None:        # dedup: keep the existing page
                node["tick"] = self._tick
                continue
            parent = key[0] if key[0] in self._nodes else None
            self._nodes[key] = {"page": int(block_row[i]), "parent": parent,
                                "children": 0, "tick": self._tick}
            if parent is not None:
                self._nodes[parent]["children"] += 1
            self.pool.ref_page(int(block_row[i]))

    def evict(self, n_pages: int) -> int:
        """Free up to ``n_pages`` pages by dropping LRU leaf nodes whose
        page only the cache still references (rc == 1); returns pages
        freed. Leaves shared by live sequences are left pinned — dropping
        them would free nothing and only destroy future reuse."""
        freed = 0
        while freed < n_pages:
            leaves = [(node["tick"], key) for key, node in
                      self._nodes.items()
                      if node["children"] == 0
                      and self.pool._page_rc[node["page"]] == 1]
            if not leaves:
                break
            _, key = min(leaves)
            node = self._nodes.pop(key)
            if node["parent"] is not None:
                self._nodes[node["parent"]]["children"] -= 1
            self.pool.unref_page(node["page"])
            freed += 1
        return freed


class ServingEngine:
    """Drive ``model`` (a GenerationMixin Layer) as a continuous-batching
    server. ``submit`` enqueues; each ``step`` admits waiting requests
    into free slots and decodes one token for every active slot;
    ``run`` steps until drained and returns {rid: tokens}."""

    def __init__(self, model, max_batch: int = 4, page_size: int = 64,
                 num_pages: Optional[int] = None, max_seq_len: int = 1024,
                 prefix_cache: bool = False):
        from ..jit import ensure_live

        self.model = model
        self.max_batch = max_batch
        self.max_seq_len = max_seq_len
        spec = model.cache_spec()
        if num_pages is None:
            num_pages = 1 + max_batch * (-(-max_seq_len // page_size))
        params, buffers = model.raw_state()
        ensure_live(params, "call step.sync_to_model() first.")
        self._params, self._buffers = params, buffers
        dtype = jnp.result_type(next(iter(params.values())))
        self.pool = PagedKVCache(
            num_layers=len(spec), num_pages=num_pages, page_size=page_size,
            num_kv_heads=spec[0][0], head_dim=spec[0][1],
            max_batch=max_batch, max_seq_len=max_seq_len, dtype=dtype,
            reserve_null_page=True)
        maxpos = getattr(getattr(model, "config", None),
                         "max_position_embeddings", None)
        if maxpos is not None and max_seq_len > maxpos:
            raise ValueError(
                f"engine max_seq_len ({max_seq_len}) exceeds the model's "
                f"max_position_embeddings ({maxpos})")
        self._slots: List[Optional[Request]] = [None] * max_batch
        self._queue: List[Request] = []
        self._results: Dict[int, List[int]] = {}
        self._last_tok = np.zeros((max_batch,), np.int32)
        self._next_rid = 0
        self._prefill_jit = None
        self._decode_jit = None
        self._prefix = PrefixCache(self.pool) if prefix_cache else None

    # ------------------------------------------------------------ frontend
    def submit(self, prompt, max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None) -> int:
        prompt = np.asarray(
            prompt._value if hasattr(prompt, "_value") else prompt,
            np.int32).reshape(-1)
        if len(prompt) + max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds engine max_seq_len "
                f"({self.max_seq_len})")
        # a request that can never fit would deadlock FIFO admission
        need = -(-(len(prompt) + max_new_tokens) // self.pool.page_size)
        usable = self.pool.num_pages - 1        # null page reserved
        if need > min(usable, self.pool.max_pages_per_seq):
            raise ValueError(
                f"request needs {need} pages but the pool can ever offer "
                f"{min(usable, self.pool.max_pages_per_seq)}")
        rid = self._next_rid
        self._next_rid += 1
        self._queue.append(Request(rid, prompt, int(max_new_tokens),
                                   eos_token_id))
        return rid

    def has_work(self) -> bool:
        return bool(self._queue) or any(s is not None for s in self._slots)

    def run(self) -> Dict[int, List[int]]:
        while self.has_work():
            self.step()
        out, self._results = self._results, {}
        return out

    # ----------------------------------------------------------- internals
    def _pools(self):
        return [(self.pool.k_pages[i], self.pool.v_pages[i])
                for i in range(len(self.pool.k_pages))]

    def _store(self, states) -> None:
        for i, st in enumerate(states):
            self.pool.k_pages[i] = _val(st.k_pages)
            self.pool.v_pages[i] = _val(st.v_pages)

    def _admit_shared(self, req: Request, slot: int, pages: List[int],
                      n_cached: int) -> None:
        """Prefix-cache admission: adopt the cached prompt pages read-only
        and teacher-force the remaining suffix through the ordinary decode
        step (one token per engine step) — no new compiled program, and
        the cached portion's prefill compute is skipped entirely. The
        model output while suffix tokens are pending is a prompt-position
        logit and is discarded; the step that feeds the LAST suffix token
        emits the first generated token."""
        self.pool.adopt_shared(slot, pages)
        self.pool.seq_lens[slot] = n_cached
        suffix = req.prompt[n_cached:]
        self.pool.allocate(slot, len(suffix) + req.max_new_tokens)
        self._last_tok[slot] = int(suffix[0])
        req.pending = [int(t) for t in suffix[1:]]
        req.slot = slot
        self._slots[slot] = req

    def _prefill(self, req: Request, slot: int) -> None:
        from ..jit import functional_call

        if self._prefix is not None:
            pages, n_cached = self._prefix.lookup(req.prompt)
            # never cover the WHOLE prompt: the first generated token's
            # logits are not cached, so at least one prompt token must go
            # through compute
            while pages and n_cached >= len(req.prompt):
                pages = pages[:-1]
                n_cached -= self.pool.page_size
            # coverage threshold: the suffix replays one token per decode
            # step, so a barely-covered long prompt would trade one b=1
            # prefill for hundreds of full-batch steps — take the shared
            # path only when the replay is small (a couple of pages) or
            # the cached part dominates it
            suffix_len = len(req.prompt) - n_cached
            if pages and suffix_len <= max(2 * self.pool.page_size,
                                           n_cached):
                self._admit_shared(req, slot, pages, n_cached)
                return

        p = len(req.prompt)
        fn = self._prefill_jit
        if fn is None:
            def run(params, buffers, ids, pools, bt, sl):
                states = [PagedDecodeState(k, v, bt, sl) for k, v in pools]
                logits, states = functional_call(
                    self.model, params, ids, states, jnp.int32(0),
                    buffers=buffers, method="forward_with_cache")
                return (jnp.argmax(logits[0, -1].astype(jnp.float32)),
                        states)
            # jit itself caches one compilation per prompt length
            # (bucket/pad prompts in production to bound that set).
            # Donate ONLY the pools (each buffer appears once there; bt/sl
            # are shared by every layer's state and must not be donated):
            # page writes then alias the pool in place
            fn = self._prefill_jit = jax.jit(run, donate_argnums=(3,))

        self.pool.allocate(slot, p + req.max_new_tokens)
        bt = jnp.asarray(self.pool.block_tables[slot:slot + 1])
        tok, states = fn(self._params, self._buffers,
                         jnp.asarray(req.prompt[None]), self._pools(),
                         bt, jnp.zeros((1,), jnp.int32))
        # b=1 prefill wrote THROUGH slot's block table into the shared
        # pool arrays; adopt them and the slot's bookkeeping
        self._store(states)
        self.pool.seq_lens[slot] = p
        self._last_tok[slot] = int(tok)
        req.tokens.append(int(tok))
        req.slot = slot
        self._slots[slot] = req
        if self._prefix is not None:
            # pin this prompt's full pages for future shared admissions
            # (they are immutable: later writes land at seq_len and up)
            self._prefix.register(req.prompt, self.pool.block_tables[slot])
        self._finish_if_done(req)

    def _finish_if_done(self, req: Request) -> None:
        done = len(req.tokens) >= req.max_new_tokens or (
            req.eos_token_id is not None
            and req.tokens and req.tokens[-1] == req.eos_token_id)
        if done and req.slot is not None:
            self.pool.free_sequence(req.slot)
            self._slots[req.slot] = None
            self._results[req.rid] = req.tokens
            req.slot = None

    def step(self) -> None:
        from ..jit import functional_call

        # admission: fill every free slot that has pages available
        for slot in range(self.max_batch):
            if self._slots[slot] is None and self._queue:
                req = self._queue[0]
                need = -(-(len(req.prompt) + req.max_new_tokens)
                         // self.pool.page_size)
                if need > self.pool.free_page_count() and self._prefix:
                    # cached-but-unshared pages are reclaimable capacity
                    self._prefix.evict(need - self.pool.free_page_count())
                if need > self.pool.free_page_count():
                    break           # wait for pages (FIFO, no starvation)
                self._queue.pop(0)
                self._prefill(req, slot)

        active = [s for s in self._slots if s is not None]
        if not active:
            return

        if self._decode_jit is None:
            def run(params, buffers, toks, pools, bt, sl):
                states = [PagedDecodeState(k, v, bt, sl) for k, v in pools]
                # offset=None -> per-slot positions from states.seq_lens
                logits, states = functional_call(
                    self.model, params, toks, states, None,
                    buffers=buffers, method="forward_with_cache")
                return (jnp.argmax(logits[:, -1].astype(jnp.float32),
                                   axis=-1), states)
            # donate only the pools (see _prefill): per-token page writes
            # alias in place instead of copying every pool every token
            self._decode_jit = jax.jit(run, donate_argnums=(3,))

        bt = jnp.asarray(self.pool.block_tables[:self.max_batch])
        sl = jnp.asarray(self.pool.seq_lens[:self.max_batch])
        toks, states = self._decode_jit(
            self._params, self._buffers,
            jnp.asarray(self._last_tok[:, None]), self._pools(), bt, sl)
        self._store(states)
        toks = np.asarray(toks)

        for slot, req in enumerate(self._slots):
            if req is None:
                continue            # idle row wrote the null page; ignore
            self.pool.seq_lens[slot] += 1
            if req.pending:
                # still teacher-forcing the prompt suffix (prefix-cache
                # admission): the model output is a prompt-position logit,
                # not a generated token — feed the next suffix token
                self._last_tok[slot] = req.pending.pop(0)
                continue
            tok = int(toks[slot])
            if self._prefix is not None and not req.tokens:
                # first generated token of a shared admission: the whole
                # prompt's KV is now written — register the suffix's full
                # pages so repeats of THIS prompt deepen the cache too
                self._prefix.register(req.prompt,
                                      self.pool.block_tables[slot])
            req.tokens.append(tok)
            self._last_tok[slot] = tok
            self._finish_if_done(req)


def _val(x):
    return x._value if hasattr(x, "_value") else x
