"""Continuous-batching serving engine over the paged KV cache.

Reference parity target: the reference ecosystem's block-attention
serving runtime (PaddleNLP llm serving over block_multihead_attention /
the vLLM scheduler design): requests ADMIT into free batch slots the
moment one opens, every decode step runs the whole fixed-shape batch with
per-slot ragged lengths, and finished sequences return their pages to the
shared pool for the next request.

TPU-native structure: exactly TWO compiled programs serve steady state —
a b=1 prefill per distinct prompt length (bucketable) and ONE fixed-shape
decode step over max_batch slots. Ragged per-slot positions ride the
paged kernel's seq_lens; idle slots write into the reserved null page and
their outputs are ignored. The host loop between tokens is where the
scheduler lives — admission, eviction, and result collection are plain
Python on block tables.

Greedy decoding (the deterministic serving mode); sampling composes the
same way via the logits hook.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..kernels.paged_attention import PagedDecodeState, PagedKVCache

__all__ = ["ServingEngine", "Request"]


@dataclass
class Request:
    rid: int
    prompt: np.ndarray                  # (P,) int32
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    tokens: List[int] = field(default_factory=list)
    slot: Optional[int] = None


class ServingEngine:
    """Drive ``model`` (a GenerationMixin Layer) as a continuous-batching
    server. ``submit`` enqueues; each ``step`` admits waiting requests
    into free slots and decodes one token for every active slot;
    ``run`` steps until drained and returns {rid: tokens}."""

    def __init__(self, model, max_batch: int = 4, page_size: int = 64,
                 num_pages: Optional[int] = None, max_seq_len: int = 1024):
        from ..jit import ensure_live

        self.model = model
        self.max_batch = max_batch
        self.max_seq_len = max_seq_len
        spec = model.cache_spec()
        if num_pages is None:
            num_pages = 1 + max_batch * (-(-max_seq_len // page_size))
        params, buffers = model.raw_state()
        ensure_live(params, "call step.sync_to_model() first.")
        self._params, self._buffers = params, buffers
        dtype = jnp.result_type(next(iter(params.values())))
        self.pool = PagedKVCache(
            num_layers=len(spec), num_pages=num_pages, page_size=page_size,
            num_kv_heads=spec[0][0], head_dim=spec[0][1],
            max_batch=max_batch, max_seq_len=max_seq_len, dtype=dtype,
            reserve_null_page=True)
        maxpos = getattr(getattr(model, "config", None),
                         "max_position_embeddings", None)
        if maxpos is not None and max_seq_len > maxpos:
            raise ValueError(
                f"engine max_seq_len ({max_seq_len}) exceeds the model's "
                f"max_position_embeddings ({maxpos})")
        self._slots: List[Optional[Request]] = [None] * max_batch
        self._queue: List[Request] = []
        self._results: Dict[int, List[int]] = {}
        self._last_tok = np.zeros((max_batch,), np.int32)
        self._next_rid = 0
        self._prefill_jit = None
        self._decode_jit = None

    # ------------------------------------------------------------ frontend
    def submit(self, prompt, max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None) -> int:
        prompt = np.asarray(
            prompt._value if hasattr(prompt, "_value") else prompt,
            np.int32).reshape(-1)
        if len(prompt) + max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds engine max_seq_len "
                f"({self.max_seq_len})")
        # a request that can never fit would deadlock FIFO admission
        need = -(-(len(prompt) + max_new_tokens) // self.pool.page_size)
        usable = self.pool.num_pages - 1        # null page reserved
        if need > min(usable, self.pool.max_pages_per_seq):
            raise ValueError(
                f"request needs {need} pages but the pool can ever offer "
                f"{min(usable, self.pool.max_pages_per_seq)}")
        rid = self._next_rid
        self._next_rid += 1
        self._queue.append(Request(rid, prompt, int(max_new_tokens),
                                   eos_token_id))
        return rid

    def has_work(self) -> bool:
        return bool(self._queue) or any(s is not None for s in self._slots)

    def run(self) -> Dict[int, List[int]]:
        while self.has_work():
            self.step()
        out, self._results = self._results, {}
        return out

    # ----------------------------------------------------------- internals
    def _pools(self):
        return [(self.pool.k_pages[i], self.pool.v_pages[i])
                for i in range(len(self.pool.k_pages))]

    def _store(self, states) -> None:
        for i, st in enumerate(states):
            self.pool.k_pages[i] = _val(st.k_pages)
            self.pool.v_pages[i] = _val(st.v_pages)

    def _prefill(self, req: Request, slot: int) -> None:
        from ..jit import functional_call

        p = len(req.prompt)
        fn = self._prefill_jit
        if fn is None:
            def run(params, buffers, ids, pools, bt, sl):
                states = [PagedDecodeState(k, v, bt, sl) for k, v in pools]
                logits, states = functional_call(
                    self.model, params, ids, states, jnp.int32(0),
                    buffers=buffers, method="forward_with_cache")
                return (jnp.argmax(logits[0, -1].astype(jnp.float32)),
                        states)
            # jit itself caches one compilation per prompt length
            # (bucket/pad prompts in production to bound that set).
            # Donate ONLY the pools (each buffer appears once there; bt/sl
            # are shared by every layer's state and must not be donated):
            # page writes then alias the pool in place
            fn = self._prefill_jit = jax.jit(run, donate_argnums=(3,))

        self.pool.allocate(slot, p + req.max_new_tokens)
        bt = jnp.asarray(self.pool.block_tables[slot:slot + 1])
        tok, states = fn(self._params, self._buffers,
                         jnp.asarray(req.prompt[None]), self._pools(),
                         bt, jnp.zeros((1,), jnp.int32))
        # b=1 prefill wrote THROUGH slot's block table into the shared
        # pool arrays; adopt them and the slot's bookkeeping
        self._store(states)
        self.pool.seq_lens[slot] = p
        self._last_tok[slot] = int(tok)
        req.tokens.append(int(tok))
        req.slot = slot
        self._slots[slot] = req
        self._finish_if_done(req)

    def _finish_if_done(self, req: Request) -> None:
        done = len(req.tokens) >= req.max_new_tokens or (
            req.eos_token_id is not None
            and req.tokens and req.tokens[-1] == req.eos_token_id)
        if done and req.slot is not None:
            self.pool.free_sequence(req.slot)
            self._slots[req.slot] = None
            self._results[req.rid] = req.tokens
            req.slot = None

    def step(self) -> None:
        from ..jit import functional_call

        # admission: fill every free slot that has pages available
        for slot in range(self.max_batch):
            if self._slots[slot] is None and self._queue:
                req = self._queue[0]
                need = -(-(len(req.prompt) + req.max_new_tokens)
                         // self.pool.page_size)
                if need > self.pool.free_page_count():
                    break           # wait for pages (FIFO, no starvation)
                self._queue.pop(0)
                self._prefill(req, slot)

        active = [s for s in self._slots if s is not None]
        if not active:
            return

        if self._decode_jit is None:
            def run(params, buffers, toks, pools, bt, sl):
                states = [PagedDecodeState(k, v, bt, sl) for k, v in pools]
                # offset=None -> per-slot positions from states.seq_lens
                logits, states = functional_call(
                    self.model, params, toks, states, None,
                    buffers=buffers, method="forward_with_cache")
                return (jnp.argmax(logits[:, -1].astype(jnp.float32),
                                   axis=-1), states)
            # donate only the pools (see _prefill): per-token page writes
            # alias in place instead of copying every pool every token
            self._decode_jit = jax.jit(run, donate_argnums=(3,))

        bt = jnp.asarray(self.pool.block_tables[:self.max_batch])
        sl = jnp.asarray(self.pool.seq_lens[:self.max_batch])
        toks, states = self._decode_jit(
            self._params, self._buffers,
            jnp.asarray(self._last_tok[:, None]), self._pools(), bt, sl)
        self._store(states)
        toks = np.asarray(toks)

        for slot, req in enumerate(self._slots):
            if req is None:
                continue            # idle row wrote the null page; ignore
            self.pool.seq_lens[slot] += 1
            tok = int(toks[slot])
            req.tokens.append(tok)
            self._last_tok[slot] = tok
            self._finish_if_done(req)


def _val(x):
    return x._value if hasattr(x, "_value") else x
