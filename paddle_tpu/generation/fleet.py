"""Fleet serving: a prefix-affinity router over N serving-engine
replicas.

One :class:`~paddle_tpu.generation.serving.ServingEngine` is
production-shaped (continuous batching, replay recovery, SLO
preemption, KV tiering) but caps the "millions of users" axis at a
single page pool and one decode stream. :class:`FleetRouter` is the
layer above: it owns N engine replicas over ONE model and places every
submitted request with

  **prefix affinity** — route to the replica whose
  :class:`~paddle_tpu.generation.serving.PrefixCache` already holds the
  longest page-aligned prefix of the prompt (probed via
  ``PrefixCache.peek(include_spilled=True)``: a host-tier hit still
  beats re-running prefill on a cold replica). System prompts and
  few-shot preambles therefore concentrate per replica, each replica's
  cache deepens on ITS tenants, and the fleet's effective prefix
  working set is the SUM of the replicas' — the r09 hit/miss counters
  (now per-``replica`` series) make the policy measurable;

  **deadline-aware load balance** as the tiebreak — among equally-hit
  replicas, place on the one with the least deadline-bearing work,
  then the least total work (a tight-deadline arrival avoids queueing
  behind other tight work it would preempt or be slack-ordered with);

  **round-robin** as the fallback — a prompt no replica has seen
  spreads uniformly (``policy="round_robin"`` forces this for every
  request: the A/B baseline arm of ``tools/serving_load.py --fleet``).

The replicas share one decode program cache (same model, same pool
geometry => same :class:`~paddle_tpu.generation.program_cache.DecodeKey`),
so N replicas compile ONCE per program kind/rung — replica fan-out adds
pools and host scheduling, never retraces.

Replica loss is a first-class event, not an exception path: the
``router_dispatch`` fault site drills it. A replica that dies
mid-drive is harvested — every completed result it still held is
banked, every live request is exported as pure host state
(``ServingEngine.export_requests``: prompt + emitted tokens) — then
rebuilt with identical geometry (cached programs re-serve, zero
retrace) while the harvested requests re-route through normal
placement across the fleet. Greedy decoding makes every re-routed
continuation bit-identical, exactly the r10 replay argument one level
up.

All router state is host-side Python; nothing here is trace-reachable.
Telemetry rides the r09 registry through ``_observe_*`` helpers, with
the fleet's own families (``fleet_requests_routed{replica,reason}``,
``fleet_replica_losses``, ``fleet_rerouted_requests``).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import observability as obs
from ..testing import faults
from .serving import OK, Request, ServingEngine

__all__ = ["FleetRouter"]


class _FleetTelemetry:
    enabled = True

    def __init__(self):
        r = obs.registry()
        self.routed = r.counter(
            "fleet_requests_routed",
            "requests placed by the fleet router, by replica and "
            "placement reason (affinity = longest cached prefix won; "
            "balance = affinity tie broken by deadline-aware load; "
            "round_robin = no replica had the prefix)",
            labels=("replica", "reason"))
        self.losses = r.counter(
            "fleet_replica_losses",
            "replica-loss events absorbed by the router (harvest + "
            "rebuild + re-route)", labels=("replica",))
        self.rerouted = r.counter(
            "fleet_rerouted_requests",
            "in-flight/queued requests re-routed from a lost replica "
            "out of its host-side state")
        self.replicas = r.gauge(
            "fleet_replicas", "engine replicas the router is driving")


class _NullFleetTelemetry:
    enabled = False

    def __init__(self):
        self.routed = obs.NULL
        self.losses = obs.NULL
        self.rerouted = self.replicas = obs.NULL


class FleetRouter:
    """Drive ``model`` behind N :class:`ServingEngine` replicas with
    prefix-affinity placement. The surface mirrors the engine's:
    ``submit`` returns a fleet-global rid; ``run_step`` pumps every
    replica one scheduler round; ``poll``/``results``/``take_results``/
    ``status`` pass through with rid translation; ``run`` steps until
    drained. Engine keyword arguments (page budget, ladder, chunk,
    ``host_tier_pages``, ``tp_degree``, ...) apply to every replica;
    ``prefix_cache`` defaults ON here — affinity is pointless without
    it. ``tp_degree > 1`` makes every replica a tensor-parallel decode
    group over the SAME mp device set (r19) — the fleet axis stays a
    routing construct, so replica loss/rebuild and re-route replay are
    untouched by tp; the per-engine ``tp`` metric label keeps a mixed
    fleet's series apart."""

    POLICIES = ("prefix_affinity", "round_robin")

    def __init__(self, model, replicas: int = 2,
                 policy: str = "prefix_affinity", **engine_kw):
        from .. import flags as _flags

        if replicas < 1:
            raise ValueError(f"need >= 1 replica, got {replicas}")
        if policy not in self.POLICIES:
            raise ValueError(
                f"unknown policy {policy!r} (have {self.POLICIES})")
        self.model = model
        self.policy = policy
        engine_kw.setdefault("prefix_cache", True)
        self._engine_kw = dict(engine_kw)
        self.engines: List[ServingEngine] = [
            self._make_engine(i) for i in range(replicas)]
        self._rr = 0                    # round-robin cursor
        self._next_rid = 0              # fleet-global rids
        # fleet rid -> (replica index, local rid), and the per-replica
        # inverse (rebuilt entries on re-route)
        self._where: Dict[int, Tuple[int, int]] = {}
        self._local2g: List[Dict[int, int]] = [
            {} for _ in range(replicas)]
        # results/statuses banked ABOVE the engines: a lost replica's
        # completed work survives its rebuild here
        self._results: Dict[int, List[int]] = {}
        self._status: Dict[int, str] = {}
        # replica-loss budget: consecutive losses with zero completed
        # work in between bound a crash-looping fleet the same way the
        # engine's no-progress retry budget bounds a wedged backend
        self.max_losses = (int(_flags.get_flag("serving_max_retries"))
                           * max(2, replicas))
        self._consec_losses = 0
        self._completed_at_loss = 0
        self.losses = 0                 # host probes (tests/benches)
        self.rerouted = 0
        self.placements: List[Tuple[int, int, str]] = []  # (rid, ri, why)
        self._f_router = faults.site("router_dispatch")
        self._m = (_FleetTelemetry() if obs.enabled()
                   else _NullFleetTelemetry())
        self._observe_fleet()

    def _make_engine(self, idx: int) -> ServingEngine:
        return ServingEngine(self.model, replica=str(idx),
                             **self._engine_kw)

    # ------------------------------------------------------------ frontend
    def submit(self, prompt, max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None,
               deadline: Optional[float] = None,
               on_token: Optional[Callable] = None,
               replica: Optional[int] = None) -> int:
        """Place one request and return its fleet-global rid. Streaming
        callbacks fire with the FLEET rid (they survive re-routing: the
        wrapper closes over it, not over any replica-local id).
        ``replica`` pins placement explicitly (tests, drains)."""
        prompt = np.asarray(
            prompt._value if hasattr(prompt, "_value") else prompt,
            np.int32).reshape(-1)
        rid = self._next_rid
        self._next_rid += 1
        if replica is not None:
            ri, why = int(replica), "pinned"
        else:
            ri, why = self._place(prompt, deadline)
        cb = None
        if on_token is not None:
            def cb(_lrid, tok, done, _cb=on_token, _g=rid):
                try:
                    _cb(_g, tok, done)
                except Exception as exc:
                    # a raising USER callback must surface to the fleet
                    # caller (the engine contract) — tag it so run_step
                    # never mistakes a client bug for a replica loss
                    exc._fleet_callback = True
                    raise
        lrid = self.engines[ri].submit(
            prompt, max_new_tokens, eos_token_id=eos_token_id,
            deadline=deadline, on_token=cb)
        self._where[rid] = (ri, lrid)
        self._local2g[ri][lrid] = rid
        self.placements.append((rid, ri, why))
        self._observe_placement(ri, why)
        return rid

    def has_work(self) -> bool:
        return any(e.has_work() for e in self.engines)

    def run_step(self) -> bool:
        """One scheduler round on every replica that has work. A
        replica that raises — the ``router_dispatch`` site, or an
        engine failure its own replay recovery could not absorb — is
        treated as LOST: its finished results bank, its live requests
        re-route across the fleet from host state, and it rebuilds
        fresh (cached programs re-serve)."""
        for ri in range(len(self.engines)):
            eng = self.engines[ri]
            if not eng.has_work():
                continue
            try:
                self._f_router.check(replica=ri)
                eng.step()
            except Exception as exc:
                if getattr(exc, "_fleet_callback", False):
                    raise       # a client callback bug, not a loss
                if self._fleet_completed() > self._completed_at_loss:
                    self._consec_losses = 0     # real progress since
                if self._consec_losses >= self.max_losses:
                    raise
                self._lose_replica(ri, exc)
        return self.has_work()

    def run(self, max_wall: Optional[float] = None
            ) -> Dict[int, List[int]]:
        """Step the fleet until drained; returns ``{rid: tokens}`` and
        retains statuses for exactly the drained rids until the next
        drain (the engine's ``run`` contract, fleet-wide)."""
        t0 = time.perf_counter()
        while self.has_work():
            if max_wall is not None and \
                    time.perf_counter() - t0 > max_wall:
                why = "fleet run(max_wall=%.3f) watchdog" % max_wall
                for eng in self.engines:
                    if eng.has_work():
                        eng._expire_all(why)
                        eng._drain_events()
                break
            self.run_step()
        out = self._drain()
        self._status = {rid: self._status[rid] for rid in out
                        if rid in self._status}
        return out

    def results(self) -> Dict[int, List[int]]:
        """Completed results so far WITHOUT draining (the exception-
        safety accessor, fleet-wide): banked loss-survivor results plus
        whatever each live replica holds."""
        out = dict(self._results)
        for ri, eng in enumerate(self.engines):
            for lrid, toks in eng.results().items():
                rid = self._local2g[ri].get(lrid)
                if rid is not None:
                    out[rid] = toks
        return out

    def take_results(self) -> Dict[int, List[int]]:
        """Drain completed results and their statuses — the
        ``run_step`` loop's collection surface (same leak contract as
        the engine's)."""
        out = self._drain()
        for rid in out:
            self._status.pop(rid, None)
        return out

    def poll(self, rid: int) -> Dict[str, object]:
        if rid in self._results:
            return {"status": self._status.get(rid, OK),
                    "tokens": list(self._results[rid]), "done": True}
        ri, lrid = self._where[rid]
        return self.engines[ri].poll(lrid)

    def status(self, rid: int) -> str:
        st = self._status.get(rid)
        if st is not None:
            return st
        loc = self._where.get(rid)
        if loc is None:
            return "PENDING"
        ri, lrid = loc
        return self.engines[ri].status(lrid)

    def statuses(self) -> Dict[int, str]:
        out = dict(self._status)
        for rid, (ri, lrid) in self._where.items():
            out[rid] = self.engines[ri].status(lrid)
        return out

    # ----------------------------------------------------------- placement
    def _place(self, prompt: np.ndarray,
               deadline: Optional[float]) -> Tuple[int, str]:
        """Prefix affinity -> deadline-aware load tiebreak ->
        round-robin fallback (or pure round-robin under that policy)."""
        if self.policy == "round_robin" or len(self.engines) == 1:
            return self._rr_next(), "round_robin"
        best, cands = 0, []
        for ri, eng in enumerate(self.engines):
            if eng._prefix is None:
                continue
            hit = eng._prefix.peek(prompt, include_spilled=True)
            if hit > best:
                best, cands = hit, [ri]
            elif hit == best and best > 0:
                cands.append(ri)
        if not cands:
            return self._rr_next(), "round_robin"
        if len(cands) == 1:
            return cands[0], "affinity"
        return (min(cands, key=lambda ri: self._load_key(ri, deadline)),
                "balance")

    def _load_key(self, ri: int, deadline: Optional[float]):
        """Deadline-aware load: a deadline-bearing arrival avoids the
        replica with the most deadline-bearing work first (that is the
        work it would be slack-ordered against or have to preempt),
        then total work; replica index breaks exact ties."""
        tight, total = self.engines[ri].load()
        return ((tight, total, ri) if deadline is not None
                else (total, tight, ri))

    def _rr_next(self) -> int:
        ri = self._rr % len(self.engines)
        self._rr += 1
        return ri

    # ------------------------------------------------------- replica loss
    def _fleet_completed(self) -> int:
        """Completed requests visible fleet-wide right now: banked
        loss survivors plus every live replica's undrained results —
        the progress signal the loss budget keys on."""
        return (len(self._results)
                + sum(len(e._results) for e in self.engines))

    def _lose_replica(self, ri: int, exc: Exception) -> None:
        """Absorb one replica loss: bank its completed work, export its
        live requests as host state, rebuild it with identical geometry
        (the process program cache re-serves every compiled step), and
        re-route the exports through normal placement. The loss budget
        counts CONSECUTIVE losses with no completed work anywhere in
        the fleet in between — a healthy replica merely surviving its
        own step must not reset the bound, or a persistent crash loop
        beside one live replica would never trip it (``run_step``
        applies the progress reset BEFORE its budget check)."""
        eng = self.engines[ri]
        st = eng.statuses()
        for lrid, toks in eng.take_results().items():
            rid = self._local2g[ri].pop(lrid, None)
            if rid is not None:
                self._where.pop(rid, None)
                self._results[rid] = toks
                self._status[rid] = st.get(lrid, OK)
        # strip-at-export / re-bind-on-adopt: streaming callbacks are
        # engine-local, never part of the exported host bundles — pull
        # the registry off the dying engine, re-bind per request below
        callbacks = eng.take_callbacks()
        harvested = eng.export_requests()
        lost_map = self._local2g[ri]
        self._local2g[ri] = {}
        self.engines[ri] = self._make_engine(ri)
        self.losses += 1
        self._consec_losses += 1
        self._completed_at_loss = self._fleet_completed()
        self._observe_loss(ri)
        for req in harvested:
            cb = callbacks.get(req.rid)
            rid = lost_map.pop(req.rid, None)
            if rid is None:
                continue
            self._route_existing(rid, req, cb)
            self.rerouted += 1
        self._observe_reroutes(len(harvested))

    def _route_existing(self, rid: int, req: Request,
                        on_token: Optional[Callable] = None) -> None:
        """Re-route one harvested request through normal placement.
        ``inject_request`` keeps its tokens/deadline (and re-binds the
        stripped streaming callback under the fresh local rid), so the
        receiving replica replays the continuation bit-identically."""
        ri, why = self._place(req.prompt, req.deadline)
        lrid = self.engines[ri].inject_request(req, on_token=on_token)
        self._where[rid] = (ri, lrid)
        self._local2g[ri][lrid] = rid
        self.placements.append((rid, ri, why))
        self._observe_placement(ri, why)

    # ------------------------------------------------------------ internals
    def _drain(self) -> Dict[int, List[int]]:
        out, self._results = self._results, {}
        for ri, eng in enumerate(self.engines):
            st = eng.statuses()
            for lrid, toks in eng.take_results().items():
                rid = self._local2g[ri].pop(lrid, None)
                if rid is None:
                    continue
                self._where.pop(rid, None)
                out[rid] = toks
                self._status.setdefault(rid, st.get(lrid, OK))
        if out:
            # drained completions are fleet progress; the undrained
            # census just reset, so re-baseline the loss budget's mark
            self._consec_losses = 0
            self._completed_at_loss = self._fleet_completed()
        return out

    # ------------------------------------------------- telemetry helpers
    def _observe_fleet(self) -> None:
        if self._m.enabled:
            self._m.replicas.set(len(self.engines))

    def _observe_placement(self, ri: int, why: str) -> None:
        if self._m.enabled:
            self._m.routed.labels(replica=str(ri), reason=why).inc()

    def _observe_loss(self, ri: int) -> None:
        if self._m.enabled:
            self._m.losses.labels(replica=str(ri)).inc()

    def _observe_reroutes(self, n: int) -> None:
        if self._m.enabled and n:
            self._m.rerouted.inc(n)
