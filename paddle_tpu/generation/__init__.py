"""Autoregressive generation: the inference/decode half of the framework.

Reference parity targets (SURVEY.md §3.5):
  - paddle/fluid/operators/fused/fused_multi_transformer_op.cu — the fused
    decode step against a KV cache (here: ``cached_scaled_dot_product_
    attention`` + the per-model ``forward_with_cache`` hooks);
  - PaddleNLP's ``GenerationMixin.generate`` — the user-facing sampling loop.

TPU-native design: the ENTIRE generation — prefill + every decode step +
sampling — is one jitted function. The decode loop is a ``lax.scan`` with a
static trip count over static-shape ring-buffer caches, so XLA compiles one
program per (batch, prompt_len, max_new_tokens) signature and each decode
step costs one device dispatch, not one per op. Eager per-token loops are
exactly the pattern the tunnel-chip environment punishes (~ms per op);
everything here stays on-device.

Models opt in by inheriting ``GenerationMixin`` and providing:
  - ``cache_spec() -> [(num_kv_heads, head_dim), ...]`` (one per layer)
  - ``forward_with_cache(input_ids, caches, offset) -> (logits, caches)``
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["GenerationMixin"]

_NEG_INF = -1e30


def _apply_logit_adjust(lg, seen, step, repetition_penalty, min_new_tokens,
                        eos_token_id):
    """Repetition penalty over already-seen tokens (HF/reference semantics:
    positive logits divide, negative multiply) + the min-length eos mask.
    Shared by the sampling and beam paths. ``seen``: (rows, V) bool."""
    if repetition_penalty != 1.0:
        pen = jnp.where(lg > 0, lg / repetition_penalty,
                        lg * repetition_penalty)
        lg = jnp.where(seen, pen, lg)
    if eos_token_id is not None and min_new_tokens > 0:
        lg = jnp.where(
            (step < min_new_tokens)
            & (jnp.arange(lg.shape[-1]) == eos_token_id)[None, :],
            _NEG_INF, lg)
    return lg


def _top_k_filter(logits: jax.Array, k: int) -> jax.Array:
    kth = jnp.sort(logits, axis=-1)[..., -k][..., None]
    return jnp.where(logits < kth, _NEG_INF, logits)


def _top_p_filter(logits: jax.Array, top_p) -> jax.Array:
    """Nucleus filtering with a traced threshold: keep the smallest prefix of
    descending-prob tokens whose cumulative mass reaches top_p (the first
    token is always kept since the exclusive cumsum starts at 0)."""
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum_excl = jnp.cumsum(probs, axis=-1) - probs
    keep = cum_excl < top_p
    cutoff = jnp.min(jnp.where(keep, sorted_desc, jnp.inf),
                     axis=-1, keepdims=True)
    return jnp.where(logits >= cutoff, logits, _NEG_INF)


class GenerationMixin:
    """Adds jit-compiled ``generate`` to a Layer with decode hooks."""

    def init_cache(self, batch: int, max_len: int, dtype=None):
        """Zero ring-buffer KV caches: one (k, v) pair per layer, each
        (batch, max_len, num_kv_heads, head_dim)."""
        if dtype is None:
            dtype = next(iter(self.parameters())).dtype
        return [(jnp.zeros((batch, max_len, hkv, d), dtype),
                 jnp.zeros((batch, max_len, hkv, d), dtype))
                for hkv, d in self.cache_spec()]

    def generate(self, input_ids, max_new_tokens: int = 32,
                 do_sample: bool = False, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0,
                 eos_token_id: Optional[int] = None,
                 pad_token_id: Optional[int] = None,
                 repetition_penalty: float = 1.0,
                 min_new_tokens: int = 0,
                 num_beams: int = 1,
                 length_penalty: float = 1.0,
                 return_full_sequence: bool = True):
        """Greedy/sampled/beam autoregressive decode. Returns the (B, P + N)
        full sequence Tensor (or (B, N) generated tail when
        ``return_full_sequence=False``). After an ``eos_token_id`` hit a row
        emits ``pad_token_id`` for the remaining steps (shapes stay static).

        ``repetition_penalty`` > 1 divides positive (multiplies negative)
        logits of every token already present in the row (prompt included),
        HF/reference semantics. ``min_new_tokens`` masks ``eos_token_id``
        for the first N steps. ``num_beams`` > 1 switches to beam search
        (greedy over beams; ``do_sample`` must be False), scoring finished
        beams with ``sum(logprobs) / len**length_penalty``."""
        from ..core.tensor import Tensor
        from ..framework.random import next_key
        from ..jit import functional_call

        ids_val = (input_ids._value if isinstance(input_ids, Tensor)
                   else jnp.asarray(input_ids))
        if ids_val.ndim != 2:
            raise ValueError(f"input_ids must be (batch, seq), got "
                             f"{ids_val.shape}")
        b, p = ids_val.shape
        total = p + int(max_new_tokens)
        maxpos = getattr(getattr(self, "config", None),
                         "max_position_embeddings", None)
        if maxpos is not None and total > maxpos:
            raise ValueError(
                f"prompt ({p}) + max_new_tokens ({max_new_tokens}) = {total} "
                f"exceeds max_position_embeddings ({maxpos})")
        if pad_token_id is None:
            pad_token_id = eos_token_id if eos_token_id is not None else 0

        if num_beams > 1 and do_sample:
            raise ValueError("beam search is greedy over beams — "
                             "do_sample=True is not supported with "
                             "num_beams > 1 (reference raises too)")

        was_training = self.training
        self.eval()
        try:
            from ..jit import ensure_live
            params, buffers = self.raw_state()
            ensure_live(params, "call step.sync_to_model() before generate().")
            # only the knobs the selected builder consumes: spurious sig
            # entries would recompile identical programs (seconds on TPU)
            if num_beams > 1:
                sig = ("beam", b, p, int(max_new_tokens), int(num_beams),
                       eos_token_id, pad_token_id, float(length_penalty),
                       float(repetition_penalty), int(min_new_tokens))
            else:
                sig = ("sample", b, p, int(max_new_tokens), bool(do_sample),
                       int(top_k), eos_token_id, pad_token_id,
                       float(repetition_penalty), int(min_new_tokens))
            cache = getattr(self, "_generate_jit_cache", None)
            if cache is None:
                cache = self._generate_jit_cache = {}
            fn = cache.get(sig)
            if fn is None:
                if num_beams > 1:
                    fn = jax.jit(self._build_beam_generate(
                        b, p, int(max_new_tokens), int(num_beams),
                        eos_token_id, pad_token_id, float(length_penalty),
                        float(repetition_penalty), int(min_new_tokens)))
                else:
                    fn = jax.jit(self._build_generate(
                        b, p, int(max_new_tokens), bool(do_sample),
                        int(top_k), eos_token_id, pad_token_id,
                        float(repetition_penalty), int(min_new_tokens)))
                cache[sig] = fn
            toks = fn(params, buffers, ids_val, next_key(),
                      jnp.float32(temperature), jnp.float32(top_p))
        finally:
            if was_training:
                self.train()
        out = jnp.concatenate([ids_val, toks], axis=1) \
            if return_full_sequence else toks
        return Tensor(out, stop_gradient=True)

    def generate_paged(self, input_ids, max_new_tokens: int = 32,
                       page_size: int = 64, num_pages: Optional[int] = None,
                       eos_token_id: Optional[int] = None,
                       pad_token_id: Optional[int] = None,
                       return_full_sequence: bool = True):
        """Greedy decode against a PAGED KV cache (reference:
        block_multihead_attention serving). Unlike ``generate`` (one scan,
        ring buffers), the token loop runs on the host with ONE jitted
        step — the structure real serving needs: between tokens a
        scheduler may admit/evict sequences by editing block tables, and
        the pool is shared across requests. Numerics match ``generate``'s
        greedy path exactly (tested)."""
        from ..core.tensor import Tensor
        from ..jit import ensure_live, functional_call
        from ..kernels.paged_attention import PagedDecodeState, PagedKVCache

        ids_val = (input_ids._value if isinstance(input_ids, Tensor)
                   else jnp.asarray(input_ids))
        b, p = ids_val.shape
        n_new = int(max_new_tokens)
        total = p + n_new
        maxpos = getattr(getattr(self, "config", None),
                         "max_position_embeddings", None)
        if maxpos is not None and total > maxpos:
            raise ValueError(
                f"prompt ({p}) + max_new_tokens ({n_new}) = {total} "
                f"exceeds max_position_embeddings ({maxpos})")
        if n_new == 0:
            return Tensor(ids_val if return_full_sequence
                          else ids_val[:, :0], stop_gradient=True)
        spec = self.cache_spec()
        if num_pages is None:
            num_pages = b * (-(-total // page_size))
        if pad_token_id is None:
            pad_token_id = eos_token_id if eos_token_id is not None else 0

        was_training = self.training
        self.eval()
        try:
            params, buffers = self.raw_state()
            ensure_live(params, "call step.sync_to_model() before "
                                "generate_paged().")
            dtype = jnp.result_type(next(iter(params.values())))
            mgr = PagedKVCache(
                num_layers=len(spec), num_pages=num_pages,
                page_size=page_size, num_kv_heads=spec[0][0],
                head_dim=spec[0][1], max_batch=b, max_seq_len=total,
                dtype=dtype)
            for s_ in range(b):
                mgr.allocate(s_, total)
            bt = jnp.asarray(mgr.block_tables[:b])
            zeros = jnp.zeros((b,), jnp.int32)
            states = [PagedDecodeState(mgr.k_pages[i], mgr.v_pages[i],
                                       bt, zeros)
                      for i in range(len(spec))]

            cache = getattr(self, "_generate_jit_cache", None)
            if cache is None:
                cache = self._generate_jit_cache = {}
            sig = ("paged", b, p, page_size, num_pages)
            fns = cache.get(sig)
            if fns is None:
                def run(params, buffers, ids, states, offset):
                    logits, states = functional_call(
                        self, params, ids, states, offset, buffers=buffers,
                        method="forward_with_cache")
                    return jnp.argmax(
                        logits[:, -1].astype(jnp.float32), axis=-1), states

                # one wrapper serves both phases (S=p and S=1 retrace
                # under the same jit); cached per signature like generate
                fns = cache[sig] = jax.jit(run)
            prefill = step = fns
            tok, states = prefill(params, buffers, ids_val, states,
                                  jnp.int32(0))
            tok = tok.astype(ids_val.dtype)
            toks = [tok]
            finished = ((tok == eos_token_id) if eos_token_id is not None
                        else jnp.zeros((b,), bool))
            for i in range(1, n_new):
                nxt, states = step(params, buffers, tok[:, None], states,
                                   jnp.int32(p + i - 1))
                nxt = nxt.astype(tok.dtype)
                nxt = jnp.where(finished,
                                jnp.asarray(pad_token_id, tok.dtype), nxt)
                if eos_token_id is not None:
                    finished = finished | (nxt == eos_token_id)
                toks.append(nxt)
                tok = nxt
            gen = jnp.stack(toks, axis=1)
        finally:
            if was_training:
                self.train()
        out = (jnp.concatenate([ids_val, gen], axis=1)
               if return_full_sequence else gen)
        return Tensor(out, stop_gradient=True)

    def generate_speculative(self, input_ids, draft_model,
                             max_new_tokens: int = 32,
                             num_speculative_tokens: int = 4,
                             return_full_sequence: bool = True):
        """Greedy speculative decoding (reference ecosystem: PaddleNLP
        speculative/draft-model inference; Leviathan et al.): a small
        ``draft_model`` proposes ``num_speculative_tokens`` tokens per
        round, the target verifies them in ONE cached forward, and the
        longest agreeing prefix plus the target's correction are
        accepted. Greedy speculation is LOSSLESS — the output equals
        ``generate(..., do_sample=False)`` token for token (tested);
        rounds cost one draft pass + one target pass for up to γ+1
        tokens of progress.

        Cache discipline: both models keep static ring buffers; rejected
        positions simply hold garbage k/v beyond the valid length and
        are overwritten by later writes (attention masks at the valid
        length). Round invariants — target cache holds ``seq[:L-1]``,
        draft cache holds ``seq[:L-1]`` too (the draft consumed exactly
        the accepted prefix minus the newest token: ``M = L_old + a``
        and ``L = L_old + a + 1`` keep ``L - M == 1`` every round) — so
        each round is ONE single-token draft feed + g-1 scan proposals
        + ONE (g+1)-token target verify, all from cached compilations.
        Single-sequence only (per-row acceptance lengths diverge in a
        batch); no eos short-circuit (decode runs to max_new_tokens)."""
        import numpy as np

        from ..core.tensor import Tensor
        from ..jit import ensure_live, functional_call

        g = int(num_speculative_tokens)
        ids_val = (input_ids._value if isinstance(input_ids, Tensor)
                   else jnp.asarray(input_ids))
        b, p = ids_val.shape
        if b != 1:
            raise ValueError("generate_speculative supports batch=1 "
                             "(per-row acceptance lengths diverge)")
        n_new = int(max_new_tokens)
        cap = p + n_new + g + 2   # slack: a round may overshoot n_new
        maxpos = getattr(getattr(self, "config", None),
                         "max_position_embeddings", None)
        if maxpos is not None and cap > maxpos:
            raise ValueError(
                f"prompt ({p}) + max_new_tokens ({n_new}) + speculative "
                f"slack ({g + 2}) = {cap} exceeds "
                f"max_position_embeddings ({maxpos})")

        def setup(model):
            params, buffers = model.raw_state()
            ensure_live(params, "call step.sync_to_model() first.")
            dtype = jnp.result_type(next(iter(params.values())))
            caches = [(jnp.zeros((1, cap, hkv, d), dtype),
                       jnp.zeros((1, cap, hkv, d), dtype))
                      for hkv, d in model.cache_spec()]
            return params, buffers, caches

        def build_fns():
            @jax.jit
            def prefill_t(params, buffers, ids, caches):
                logits, caches = functional_call(
                    self, params, ids, caches, jnp.int32(0),
                    buffers=buffers, method="forward_with_cache")
                return jnp.argmax(logits[0, -1].astype(jnp.float32)), caches

            @jax.jit
            def prefill_d(params, buffers, ids, caches):
                _, caches = functional_call(
                    draft_model, params, ids, caches, jnp.int32(0),
                    buffers=buffers, method="forward_with_cache")
                return caches

            @jax.jit
            def draft_round(params, buffers, tok_in, offset, caches):
                """Feed the newest accepted token at ``offset`` (the
                draft's only gap — see the L-M invariant), then propose
                g greedy tokens."""
                logits, caches = functional_call(
                    draft_model, params, tok_in[None, None], caches,
                    offset, buffers=buffers, method="forward_with_cache")
                tok = jnp.argmax(
                    logits[0, -1].astype(jnp.float32)).astype(tok_in.dtype)

                def body(carry, i):
                    tok, caches = carry
                    lg, caches = functional_call(
                        draft_model, params, tok[None, None], caches,
                        offset + 1 + i, buffers=buffers,
                        method="forward_with_cache")
                    nxt = jnp.argmax(
                        lg[0, -1].astype(jnp.float32)).astype(tok.dtype)
                    return (nxt, caches), tok

                (last, caches), emitted = lax.scan(
                    body, (tok, caches), jnp.arange(g - 1, dtype=jnp.int32))
                return jnp.append(emitted, last), caches

            @jax.jit
            def verify_round(params, buffers, chunk, offset, caches):
                """Target forward over [seq[L-1], d1..dg]: greedy picks
                AFTER each prefix."""
                logits, caches = functional_call(
                    self, params, chunk, caches, offset, buffers=buffers,
                    method="forward_with_cache")
                return jnp.argmax(
                    logits[0].astype(jnp.float32), axis=-1), caches

            return prefill_t, prefill_d, draft_round, verify_round

        cache = getattr(self, "_generate_jit_cache", None)
        if cache is None:
            cache = self._generate_jit_cache = {}
        sig = ("spec", p, g, cap)
        entry = cache.get(sig)
        # the jitted fns close over draft_model: rebuild if the caller
        # passes a different draft (identity-checked, not id()-keyed)
        if entry is None or entry[0] is not draft_model:
            entry = (draft_model, build_fns())
            cache[sig] = entry
        prefill_t, prefill_d, draft_round, verify_round = entry[1]

        was_training = (self.training, draft_model.training)
        self.eval()
        draft_model.eval()
        try:
            tp, tb, t_caches = setup(self)
            dp, db, d_caches = setup(draft_model)

            # prompt
            first, t_caches = prefill_t(tp, tb, ids_val, t_caches)
            d_caches = prefill_d(dp, db, ids_val, d_caches)
            np_ids = np.asarray(ids_val)
            idt = ids_val.dtype
            seq = list(np_ids[0])
            seq.append(int(first))
            L = len(seq)     # accepted length; both caches hold seq[:L-1]

            vchunk = np.zeros((1, g + 1), np_ids.dtype)
            while len(seq) - p < n_new:
                props, d_caches = draft_round(
                    dp, db, jnp.asarray(seq[L - 1], idt),
                    jnp.int32(L - 1), d_caches)
                props_np = np.asarray(props)[:g]

                vchunk[0, 0] = seq[L - 1]
                vchunk[0, 1:g + 1] = props_np
                greedy, t_caches = verify_round(
                    tp, tb, jnp.asarray(vchunk, idt), jnp.int32(L - 1),
                    t_caches)
                greedy_np = np.asarray(greedy)

                a = 0
                while a < g and int(props_np[a]) == int(greedy_np[a]):
                    a += 1
                seq.extend([int(x) for x in props_np[:a]])
                seq.append(int(greedy_np[a]))
                L = len(seq)

            gen = jnp.asarray(np.asarray(seq[p:p + n_new],
                                         np_ids.dtype))[None, :]
        finally:
            if was_training[0]:
                self.train()
            if was_training[1]:
                draft_model.train()
        out = (jnp.concatenate([ids_val, gen], axis=1)
               if return_full_sequence else gen)
        return Tensor(out, stop_gradient=True)

    def _build_generate(self, b, p, n_new, do_sample, top_k,
                        eos_token_id, pad_token_id,
                        repetition_penalty=1.0, min_new_tokens=0):
        from ..jit import functional_call

        def adjust(lg, seen, step):
            return _apply_logit_adjust(lg, seen, step, repetition_penalty,
                                       min_new_tokens, eos_token_id)

        def select(logits, key, temperature, top_p, seen, step):
            lg = adjust(logits.astype(jnp.float32), seen, step)
            if not do_sample:
                return jnp.argmax(lg, axis=-1)
            lg = lg / jnp.maximum(temperature, 1e-6)
            if top_k > 0:
                lg = _top_k_filter(lg, top_k)
            lg = _top_p_filter(lg, top_p)
            return jax.random.categorical(key, lg, axis=-1)

        def gen(params, buffers, ids, key, temperature, top_p):
            total = p + n_new
            dtype = jnp.result_type(next(iter(params.values())))
            caches = [(jnp.zeros((b, total, hkv, d), dtype),
                       jnp.zeros((b, total, hkv, d), dtype))
                      for hkv, d in self.cache_spec()]
            track = repetition_penalty != 1.0

            # prefill: writes cache positions [0, p), predicts token p
            logits, caches = functional_call(
                self, params, ids, caches, jnp.int32(0), buffers=buffers,
                method="forward_with_cache")
            # vocab from the logits, NOT self.config: the mixin contract
            # only requires cache_spec + forward_with_cache. The penalty
            # applies to prompt tokens too — HF/reference semantics.
            seen = (jnp.zeros((b, logits.shape[-1]), bool).at[
                        jnp.arange(b)[:, None], ids].set(True)
                    if track else jnp.zeros((b, 1), bool))
            key, sub = jax.random.split(key)
            tok = select(logits[:, -1], sub, temperature, top_p, seen,
                         jnp.int32(0)).astype(ids.dtype)
            if track:
                seen = seen.at[jnp.arange(b), tok].set(True)
            if eos_token_id is not None:
                finished = tok == eos_token_id
            else:
                finished = jnp.zeros((b,), bool)

            def body(carry, step):
                tok, caches, off, key, finished, seen = carry
                logits, caches = functional_call(
                    self, params, tok[:, None], caches, off, buffers=buffers,
                    method="forward_with_cache")
                key, sub = jax.random.split(key)
                nxt = select(logits[:, -1], sub, temperature, top_p, seen,
                             step).astype(tok.dtype)
                nxt = jnp.where(finished, jnp.asarray(pad_token_id, tok.dtype),
                                nxt)
                if track:
                    seen = seen.at[jnp.arange(b), nxt].set(True)
                if eos_token_id is not None:
                    finished = finished | (nxt == eos_token_id)
                return (nxt, caches, off + 1, key, finished, seen), nxt

            (_, _, _, _, _, _), rest = lax.scan(
                body, (tok, caches, jnp.int32(p), key, finished, seen),
                jnp.arange(1, n_new), length=n_new - 1)
            return jnp.concatenate([tok[:, None],
                                    jnp.moveaxis(rest, 0, 1)], axis=1)

        return gen

    def _build_beam_generate(self, b, p, n_new, beams, eos_token_id,
                             pad_token_id, length_penalty,
                             repetition_penalty=1.0, min_new_tokens=0):
        """Beam search as one jitted program (reference: PaddleNLP
        GenerationMixin beam_search). Beams ride the batch dimension of the
        KV caches ((b*beams, ...)), reindexed with take_along_axis at every
        step; finished beams can only extend with pad at zero extra score.
        Final: best beam by sum(logprobs) / len**length_penalty, counting
        tokens up to and including eos."""
        from ..jit import functional_call

        eos = eos_token_id
        pad = pad_token_id if pad_token_id is not None else (
            eos if eos is not None else 0)

        def adjust(lg, seen, step):
            return _apply_logit_adjust(lg, seen, step, repetition_penalty,
                                       min_new_tokens, eos)

        def gen(params, buffers, ids, key, temperature, top_p):
            del key, temperature, top_p   # greedy over beams
            total = p + n_new
            dtype = jnp.result_type(next(iter(params.values())))
            bb = b * beams
            caches = [(jnp.zeros((bb, total, hkv, d), dtype),
                       jnp.zeros((bb, total, hkv, d), dtype))
                      for hkv, d in self.cache_spec()]
            ids_t = jnp.repeat(ids, beams, axis=0)        # (bb, p)
            track = repetition_penalty != 1.0

            logits, caches = functional_call(
                self, params, ids_t, caches, jnp.int32(0), buffers=buffers,
                method="forward_with_cache")
            vocab = logits.shape[-1]     # NOT self.config: mixin contract
            seen = (jnp.zeros((bb, vocab), bool).at[
                        jnp.arange(bb)[:, None], ids_t].set(True)
                    if track else jnp.zeros((bb, 1), bool))
            lp = jax.nn.log_softmax(
                adjust(logits[:, -1].astype(jnp.float32), seen,
                       jnp.int32(0)), axis=-1)            # (bb, V)
            lp = lp.reshape(b, beams, vocab)
            # all beams of a batch row are identical after prefill: keep
            # only beam 0's distribution so the top-k picks DISTINCT tokens
            first = jnp.where(
                (jnp.arange(beams) == 0)[None, :, None], lp[:, :1], _NEG_INF)
            scores, idx = lax.top_k(first.reshape(b, -1), beams)  # (b, beams)
            tok = (idx % vocab).astype(ids.dtype)                 # (b, beams)
            finished = (tok == eos) if eos is not None \
                else jnp.zeros((b, beams), bool)
            lengths = jnp.ones((b, beams), jnp.int32)
            if track:
                seen = seen.at[jnp.arange(bb), tok.reshape(bb)].set(True)

            def body(carry, step):
                tok, caches, off, scores, finished, lengths, seen = carry
                logits, caches = functional_call(
                    self, params, tok.reshape(bb)[:, None], caches, off,
                    buffers=buffers, method="forward_with_cache")
                lp = jax.nn.log_softmax(
                    adjust(logits[:, -1].astype(jnp.float32), seen, step),
                    axis=-1).reshape(b, beams, vocab)
                # finished beams: only pad continues, at zero extra score
                pad_row = jnp.where(jnp.arange(vocab) == pad, 0.0, _NEG_INF)
                lp = jnp.where(finished[:, :, None], pad_row[None, None], lp)
                cand = scores[:, :, None] + lp                # (b, beams, V)
                scores, idx = lax.top_k(cand.reshape(b, -1), beams)
                src = idx // vocab                            # beam origin
                nxt = (idx % vocab).astype(tok.dtype)
                # reorder every per-beam state to the chosen origins
                gather = lambda x: jnp.take_along_axis(x, src, axis=1)
                finished = gather(finished)
                lengths = gather(lengths)
                flat_src = (jnp.arange(b)[:, None] * beams + src).reshape(bb)
                caches = [(k[flat_src], v[flat_src]) for k, v in caches]
                if track:
                    seen = seen[flat_src].at[
                        jnp.arange(bb), nxt.reshape(bb)].set(True)
                lengths = jnp.where(finished, lengths, lengths + 1)
                if eos is not None:
                    finished = finished | (nxt == eos)
                return ((nxt, caches, off + 1, scores, finished, lengths,
                         seen), (nxt, src))

            tok0 = tok                              # position-0 tokens
            carry = (tok, caches, jnp.int32(p), scores, finished, lengths,
                     seen)
            (_, _, _, scores, finished, lengths, _), (steps, origins) = \
                lax.scan(body, carry, jnp.arange(1, n_new), length=n_new - 1)
            # backtrack: follow each final beam's origin chain to rebuild
            # its token sequence ((n_new-1, b, beams) steps/origins)
            def back(carry, xs):
                beam_idx = carry                    # (b, beams) into step t
                step_tok, step_src = xs
                toks = jnp.take_along_axis(step_tok, beam_idx, axis=1)
                beam_idx = jnp.take_along_axis(step_src, beam_idx, axis=1)
                return beam_idx, toks

            init = jnp.tile(jnp.arange(beams)[None], (b, 1))
            first_beam, rev = lax.scan(back, init, (steps, origins),
                                       reverse=True)
            first_tok = jnp.take_along_axis(tok0, first_beam, axis=1)
            seqs = jnp.concatenate([first_tok[None], rev], axis=0)  # (n,b,beams)
            seqs = jnp.moveaxis(seqs, 0, 2)                  # (b, beams, n)
            norm = scores / (lengths.astype(jnp.float32) ** length_penalty)
            best = jnp.argmax(norm, axis=1)                  # (b,)
            out = jnp.take_along_axis(
                seqs, best[:, None, None], axis=1)[:, 0]     # (b, n_new)
            return out

        return gen
