"""Autoregressive generation: the inference/decode half of the framework.

Reference parity targets (SURVEY.md §3.5):
  - paddle/fluid/operators/fused/fused_multi_transformer_op.cu — the fused
    decode step against a KV cache (here: ``cached_scaled_dot_product_
    attention`` + the per-model ``forward_with_cache`` hooks);
  - PaddleNLP's ``GenerationMixin.generate`` — the user-facing sampling loop.

TPU-native design: the ENTIRE generation — prefill + every decode step +
sampling — is one jitted function. The decode loop is a ``lax.scan`` with a
static trip count over static-shape ring-buffer caches, so XLA compiles one
program per (batch, prompt_len, max_new_tokens) signature and each decode
step costs one device dispatch, not one per op. Eager per-token loops are
exactly the pattern the tunnel-chip environment punishes (~ms per op);
everything here stays on-device.

Models opt in by inheriting ``GenerationMixin`` and providing:
  - ``cache_spec() -> [(num_kv_heads, head_dim), ...]`` (one per layer)
  - ``forward_with_cache(input_ids, caches, offset) -> (logits, caches)``
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["GenerationMixin"]

_NEG_INF = -1e30


def _top_k_filter(logits: jax.Array, k: int) -> jax.Array:
    kth = jnp.sort(logits, axis=-1)[..., -k][..., None]
    return jnp.where(logits < kth, _NEG_INF, logits)


def _top_p_filter(logits: jax.Array, top_p) -> jax.Array:
    """Nucleus filtering with a traced threshold: keep the smallest prefix of
    descending-prob tokens whose cumulative mass reaches top_p (the first
    token is always kept since the exclusive cumsum starts at 0)."""
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum_excl = jnp.cumsum(probs, axis=-1) - probs
    keep = cum_excl < top_p
    cutoff = jnp.min(jnp.where(keep, sorted_desc, jnp.inf),
                     axis=-1, keepdims=True)
    return jnp.where(logits >= cutoff, logits, _NEG_INF)


class GenerationMixin:
    """Adds jit-compiled ``generate`` to a Layer with decode hooks."""

    def init_cache(self, batch: int, max_len: int, dtype=None):
        """Zero ring-buffer KV caches: one (k, v) pair per layer, each
        (batch, max_len, num_kv_heads, head_dim)."""
        if dtype is None:
            dtype = next(iter(self.parameters())).dtype
        return [(jnp.zeros((batch, max_len, hkv, d), dtype),
                 jnp.zeros((batch, max_len, hkv, d), dtype))
                for hkv, d in self.cache_spec()]

    def generate(self, input_ids, max_new_tokens: int = 32,
                 do_sample: bool = False, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0,
                 eos_token_id: Optional[int] = None,
                 pad_token_id: Optional[int] = None,
                 return_full_sequence: bool = True):
        """Greedy/sampled autoregressive decode. Returns the (B, P + N)
        full sequence Tensor (or (B, N) generated tail when
        ``return_full_sequence=False``). After an ``eos_token_id`` hit a row
        emits ``pad_token_id`` for the remaining steps (shapes stay static)."""
        from ..core.tensor import Tensor
        from ..framework.random import next_key
        from ..jit import functional_call

        ids_val = (input_ids._value if isinstance(input_ids, Tensor)
                   else jnp.asarray(input_ids))
        if ids_val.ndim != 2:
            raise ValueError(f"input_ids must be (batch, seq), got "
                             f"{ids_val.shape}")
        b, p = ids_val.shape
        total = p + int(max_new_tokens)
        maxpos = getattr(getattr(self, "config", None),
                         "max_position_embeddings", None)
        if maxpos is not None and total > maxpos:
            raise ValueError(
                f"prompt ({p}) + max_new_tokens ({max_new_tokens}) = {total} "
                f"exceeds max_position_embeddings ({maxpos})")
        if pad_token_id is None:
            pad_token_id = eos_token_id if eos_token_id is not None else 0

        was_training = self.training
        self.eval()
        try:
            from ..jit import ensure_live
            params, buffers = self.raw_state()
            ensure_live(params, "call step.sync_to_model() before generate().")
            sig = (b, p, int(max_new_tokens), bool(do_sample), int(top_k),
                   eos_token_id, pad_token_id)
            cache = getattr(self, "_generate_jit_cache", None)
            if cache is None:
                cache = self._generate_jit_cache = {}
            fn = cache.get(sig)
            if fn is None:
                fn = jax.jit(self._build_generate(
                    b, p, int(max_new_tokens), bool(do_sample), int(top_k),
                    eos_token_id, pad_token_id))
                cache[sig] = fn
            toks = fn(params, buffers, ids_val, next_key(),
                      jnp.float32(temperature), jnp.float32(top_p))
        finally:
            if was_training:
                self.train()
        out = jnp.concatenate([ids_val, toks], axis=1) \
            if return_full_sequence else toks
        return Tensor(out, stop_gradient=True)

    def _build_generate(self, b, p, n_new, do_sample, top_k,
                        eos_token_id, pad_token_id):
        from ..jit import functional_call

        def select(logits, key, temperature, top_p):
            lg = logits.astype(jnp.float32)
            if not do_sample:
                return jnp.argmax(lg, axis=-1)
            lg = lg / jnp.maximum(temperature, 1e-6)
            if top_k > 0:
                lg = _top_k_filter(lg, top_k)
            lg = _top_p_filter(lg, top_p)
            return jax.random.categorical(key, lg, axis=-1)

        def gen(params, buffers, ids, key, temperature, top_p):
            total = p + n_new
            dtype = jnp.result_type(next(iter(params.values())))
            caches = [(jnp.zeros((b, total, hkv, d), dtype),
                       jnp.zeros((b, total, hkv, d), dtype))
                      for hkv, d in self.cache_spec()]

            # prefill: writes cache positions [0, p), predicts token p
            logits, caches = functional_call(
                self, params, ids, caches, jnp.int32(0), buffers=buffers,
                method="forward_with_cache")
            key, sub = jax.random.split(key)
            tok = select(logits[:, -1], sub, temperature, top_p).astype(
                ids.dtype)
            if eos_token_id is not None:
                finished = tok == eos_token_id
            else:
                finished = jnp.zeros((b,), bool)

            def body(carry, _):
                tok, caches, off, key, finished = carry
                logits, caches = functional_call(
                    self, params, tok[:, None], caches, off, buffers=buffers,
                    method="forward_with_cache")
                key, sub = jax.random.split(key)
                nxt = select(logits[:, -1], sub, temperature, top_p).astype(
                    tok.dtype)
                nxt = jnp.where(finished, jnp.asarray(pad_token_id, tok.dtype),
                                nxt)
                if eos_token_id is not None:
                    finished = finished | (nxt == eos_token_id)
                return (nxt, caches, off + 1, key, finished), nxt

            (_, _, _, _, _), rest = lax.scan(
                body, (tok, caches, jnp.int32(p), key, finished), None,
                length=n_new - 1)
            return jnp.concatenate([tok[:, None],
                                    jnp.moveaxis(rest, 0, 1)], axis=1)

        return gen
