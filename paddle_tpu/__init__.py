"""paddle_tpu — a TPU-native deep-learning framework with the API surface of
PaddlePaddle, rebuilt on jax/XLA/Pallas.

The compute path is jax (XLA + Pallas kernels); parallelism is
jax.sharding over ICI/DCN meshes; the user API mirrors ``paddle.*`` so code
written against the reference ports with an import swap.
"""

__version__ = "0.1.0"

from . import flags  # noqa: F401  (flag registry first: ops read flags)
from .flags import get_flags, set_flags  # noqa: F401
from . import jax_compat  # noqa: F401  (installs jax.shard_map on old jax)

from .core.dtype import (  # noqa: F401
    bfloat16, bool_ as bool8, complex64, complex128, DType,
    float16, float32, float64, float8_e4m3fn, float8_e5m2,
    int8, int16, int32, int64, uint8,
)
from .core.dtype import bool_, finfo, iinfo  # noqa: F401


def __getattr__(name):
    # paddle.bool without shadowing the builtin inside this module's own
    # function bodies (PEP 562)
    if name == "bool":
        return bool_
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")
from .core.place import (  # noqa: F401
    CPUPlace, CUDAPlace, Place, TPUPlace, XPUPlace, device_count,
    get_default_dtype, get_device, is_compiled_with_cuda,
    is_compiled_with_tpu, is_compiled_with_xpu, set_default_dtype, set_device,
)
from .core.tensor import Parameter, Tensor  # noqa: F401
from .nn.param_attr import ParamAttr  # noqa: F401
from .core.autograd import enable_grad, no_grad, set_grad_enabled  # noqa: F401
from .core import autograd as _autograd_mod

is_grad_enabled = _autograd_mod.is_grad_enabled

# the op surface: paddle.add / paddle.reshape / ... (also binds Tensor methods)
from .ops import *  # noqa: F401,F403
from . import ops  # noqa: F401

from .framework.random import get_cuda_rng_state, get_rng_state, seed, set_cuda_rng_state, set_rng_state  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import jit  # noqa: F401
from . import static  # noqa: F401
from . import fft  # noqa: F401
from . import signal  # noqa: F401
from . import vision  # noqa: F401
from . import distribution  # noqa: F401
from . import inference  # noqa: F401
from . import sparse  # noqa: F401
from . import amp  # noqa: F401
from . import io  # noqa: F401
from . import metric  # noqa: F401
from . import device  # noqa: F401
from . import regularizer  # noqa: F401
from . import version  # noqa: F401
from . import hub  # noqa: F401
from . import geometric  # noqa: F401
from . import audio  # noqa: F401
from . import text  # noqa: F401
from . import onnx  # noqa: F401
from .hapi import Model  # noqa: F401
from .hapi import callbacks as callbacks  # noqa: F401


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """reference: paddle.set_printoptions — numpy-backed display options
    (tensors print via numpy here)."""
    import numpy as _np
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)
from . import autograd  # noqa: F401
from .framework.io import load, save  # noqa: F401
from .framework.lazy import LazyGuard  # noqa: F401
from . import distributed  # noqa: F401
from . import hapi  # noqa: F401
from .hapi.summary import flops, summary  # noqa: F401
import importlib as _importlib
# NB: `from . import linalg` would return the ops.linalg SUBMODULE already
# bound on the package by `from .ops import *`; force the rich module
linalg = _importlib.import_module(".linalg", __name__)
from . import models  # noqa: F401
from . import incubate  # noqa: F401
from . import profiler  # noqa: F401
from . import observability  # noqa: F401  (host-side metrics + spans)
from .utils.install_check import run_check  # noqa: F401
from . import quantization  # noqa: F401


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False):
    """``paddle.grad``: returns grads of outputs w.r.t. inputs without
    touching .grad on other leaves (implemented via a scoped backward)."""
    outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    saved = [(p, p.grad, p._retain_grads) for p in ins]
    for p in ins:
        p.grad = None
        p._retain_grads = True
    from .core.autograd import backward as _backward
    _backward(list(outs), grad_outputs, retain_graph=bool(retain_graph))
    grads = []
    for p, old_grad, old_retain in saved:
        g = p.grad
        if g is None and not allow_unused:
            raise RuntimeError(f"input {p.name} is unused in the graph "
                               "(pass allow_unused=True to permit)")
        grads.append(g)
        p.grad = old_grad
        p._retain_grads = old_retain
    return grads


class dtype(DType):  # alias so paddle.dtype comparisons work
    pass


def rank(x) -> int:
    return x.ndim


_static_mode = False


def in_dynamic_mode() -> bool:
    return not _static_mode


def disable_static(place=None):
    global _static_mode
    _static_mode = False
    return None


def enable_static():
    """Static-graph compatibility mode: build under
    ``paddle.static.program_guard`` (ops are recorded by execution) and
    run with ``paddle.static.Executor``. The mode flag only flips
    ``in_dynamic_mode()`` — recording is scoped by program_guard."""
    global _static_mode
    _static_mode = True
    return None


def disable_signal_handler():
    return None


def device_guard(device=None):
    import contextlib
    return contextlib.nullcontext()


def synchronize():
    import jax
    (jax.device_put(0) + 0).block_until_ready()


# paddle.device is the real submodule (imported above); the former class
# facade is gone — everything it offered lives in device/__init__.py


def batch(reader, batch_size, drop_last=False):
    """reference: paddle.batch (deprecated reader decorator)."""
    def gen():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return gen
