"""paddle.sparse.nn (reference: python/paddle/sparse/nn/): activations and
layers over sparse tensors — applied to the nonzero values, preserving
structure."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..nn.layer import Layer
from . import _coo, _wrap_like


def _value_map(x, fn):
    m = _coo(x)
    return _wrap_like(x, jsparse.BCOO((fn(m.data), m.indices),
                                      shape=m.shape))


class ReLU(Layer):
    def forward(self, x):
        return _value_map(x, jax.nn.relu)


class ReLU6(Layer):
    def forward(self, x):
        return _value_map(x, jax.nn.relu6)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        return _value_map(x, lambda v: jax.nn.leaky_relu(v, self._slope))


class Softmax(Layer):
    """Row-wise softmax over the sparse pattern (reference:
    sparse/nn/layer/activation.py Softmax): densifies masked rows —
    zeros outside the pattern stay zero."""

    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        m = _coo(x)
        dense = m.todense()
        mask = jsparse.BCOO((jnp.ones_like(m.data, bool), m.indices),
                            shape=m.shape).todense()
        s = jnp.where(mask, dense, -jnp.inf)
        out = jax.nn.softmax(s, axis=self._axis)
        out = jnp.where(mask, out, 0.0)
        return _wrap_like(x, jsparse.bcoo_fromdense(out))
