"""paddle.sparse — COO/CSR sparse tensors
(reference: python/paddle/sparse/ — creation.py, binary.py, unary.py,
nn/functional; the C++ kernels live in paddle/phi/kernels/sparse/).

TPU-native design: sparse storage rides ``jax.experimental.sparse``
(BCOO/BCSR), whose ops lower to XLA gather/scatter/segment-sum — the TPU has
no sparse MXU path, so (like the reference's cuSPARSE fallbacks) sparse
compute is worthwhile for memory, not FLOPs. The facade keeps the reference
API: ``sparse_coo_tensor(indices, values, shape)`` with ``indices`` of shape
``[ndim, nnz]``, ``.to_dense()``, ``.indices()/.values()/.crows()/.cols()``,
elementwise add/subtract/multiply/divide on matching sparsity, ``matmul``
(sparse @ dense), ``masked_matmul``, and unary math that preserves zeros.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor, _val

__all__ = [
    "SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
    "sparse_csr_tensor", "is_same_shape", "add", "subtract", "multiply",
    "divide", "matmul", "masked_matmul", "transpose", "coalesce",
    "relu", "abs", "sin", "tan", "asin", "atan", "sinh", "tanh", "asinh",
    "atanh", "sqrt", "square", "log1p", "expm1", "neg", "pow", "cast",
]


class SparseCooTensor:
    """COO sparse tensor (reference: phi::SparseCooTensor surfaced via
    paddle.sparse.sparse_coo_tensor)."""

    def __init__(self, bcoo: jsparse.BCOO):
        self._m = bcoo

    # -------------------------------------------------------- inspection
    @property
    def shape(self):
        return list(self._m.shape)

    @property
    def dtype(self):
        return self._m.data.dtype

    @property
    def nnz(self):
        return int(self._m.nse)

    def indices(self) -> Tensor:
        # paddle layout: [ndim, nnz]; BCOO stores [nnz, ndim]
        return Tensor(self._m.indices.T, stop_gradient=True)

    def values(self) -> Tensor:
        return Tensor(self._m.data, stop_gradient=True)

    def is_sparse(self) -> bool:
        return True

    def is_sparse_coo(self) -> bool:
        return True

    def is_sparse_csr(self) -> bool:
        return False

    # ------------------------------------------------------- conversion
    def to_dense(self) -> Tensor:
        return Tensor(self._m.todense(), stop_gradient=True)

    def to_sparse_csr(self) -> "SparseCsrTensor":
        if len(self._m.shape) != 2:
            raise ValueError("to_sparse_csr needs a 2-D tensor")
        return SparseCsrTensor(jsparse.BCSR.from_bcoo(self._m))

    def coalesce(self) -> "SparseCooTensor":
        return SparseCooTensor(self._m.sum_duplicates())

    # ------------------------------------------------------------- math
    def __matmul__(self, other):
        return matmul(self, other)

    def __add__(self, other):
        return add(self, other)

    def __sub__(self, other):
        return subtract(self, other)

    def __mul__(self, other):
        return multiply(self, other)

    def __truediv__(self, other):
        return divide(self, other)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")

    def numpy(self):
        return np.asarray(self._m.todense())

    def T(self):
        return transpose(self, list(range(len(self.shape)))[::-1])

    def astype(self, dtype):
        return cast(self, dtype)


class SparseCsrTensor:
    """CSR sparse tensor (2-D) (reference: phi::SparseCsrTensor)."""

    def __init__(self, bcsr: jsparse.BCSR):
        self._m = bcsr

    @property
    def shape(self):
        return list(self._m.shape)

    @property
    def dtype(self):
        return self._m.data.dtype

    @property
    def nnz(self):
        return int(self._m.nse)

    def crows(self) -> Tensor:
        return Tensor(self._m.indptr, stop_gradient=True)

    def cols(self) -> Tensor:
        return Tensor(self._m.indices, stop_gradient=True)

    def values(self) -> Tensor:
        return Tensor(self._m.data, stop_gradient=True)

    def is_sparse(self) -> bool:
        return True

    def is_sparse_coo(self) -> bool:
        return False

    def is_sparse_csr(self) -> bool:
        return True

    def to_dense(self) -> Tensor:
        return Tensor(self._m.todense(), stop_gradient=True)

    def to_sparse_coo(self, sparse_dim: Optional[int] = None):
        return SparseCooTensor(self._m.to_bcoo())

    def __matmul__(self, other):
        return matmul(self, other)

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")

    def numpy(self):
        return np.asarray(self._m.todense())


# ------------------------------------------------------------- creation
def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True) -> SparseCooTensor:
    """(reference: python/paddle/sparse/creation.py::sparse_coo_tensor).
    ``indices``: [ndim, nnz]; ``values``: [nnz, ...dense dims]."""
    idx = jnp.asarray(_val(indices), jnp.int32)
    val = jnp.asarray(_val(values))
    if dtype is not None:
        from ..core.dtype import to_jax_dtype
        val = val.astype(to_jax_dtype(dtype))
    if idx.ndim != 2:
        raise ValueError(f"indices must be [ndim, nnz], got {idx.shape}")
    if shape is None:
        shape = tuple(int(i) for i in (idx.max(axis=1) + 1))
    m = jsparse.BCOO((val, idx.T), shape=tuple(int(s) for s in shape))
    return SparseCooTensor(m)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True) -> SparseCsrTensor:
    """(reference: python/paddle/sparse/creation.py::sparse_csr_tensor)."""
    indptr = jnp.asarray(_val(crows), jnp.int32)
    indices = jnp.asarray(_val(cols), jnp.int32)
    val = jnp.asarray(_val(values))
    if dtype is not None:
        from ..core.dtype import to_jax_dtype
        val = val.astype(to_jax_dtype(dtype))
    m = jsparse.BCSR((val, indices, indptr),
                     shape=tuple(int(s) for s in shape))
    return SparseCsrTensor(m)


def is_same_shape(x, y) -> bool:
    return list(x.shape) == list(y.shape)


def _coo(x) -> jsparse.BCOO:
    if isinstance(x, SparseCooTensor):
        return x._m
    if isinstance(x, SparseCsrTensor):
        return x._m.to_bcoo()
    raise TypeError(f"expected a sparse tensor, got {type(x)}")


def _wrap_like(x, m: jsparse.BCOO):
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(jsparse.BCSR.from_bcoo(m))
    return SparseCooTensor(m)


# ---------------------------------------------------------------- binary
def _ew(name, fn, x, y):
    """Elementwise op on two same-shape sparse tensors (union support) or
    sparse ⊕ dense scalar."""
    if isinstance(y, (int, float)):
        m = _coo(x)
        return _wrap_like(x, jsparse.BCOO((fn(m.data, y), m.indices),
                                          shape=m.shape))
    mx, my = _coo(x), _coo(y)
    if tuple(mx.shape) != tuple(my.shape):
        raise ValueError(f"{name}: shape mismatch {mx.shape} vs {my.shape}")
    # union of supports via concatenation + sum_duplicates keeps COO form
    if name in ("add", "subtract"):
        data_y = my.data if name == "add" else -my.data
        m = jsparse.BCOO(
            (jnp.concatenate([mx.data, data_y]),
             jnp.concatenate([mx.indices, my.indices])),
            shape=mx.shape).sum_duplicates()
        return _wrap_like(x, m)
    # multiply/divide need aligned supports: densify the rhs (documented
    # scope: the reference's sparse*sparse also requires same sparsity)
    dy = my.todense()
    vals = fn(mx.data, dy[tuple(mx.indices.T)])
    return _wrap_like(x, jsparse.BCOO((vals, mx.indices), shape=mx.shape))


def add(x, y):
    return _ew("add", jnp.add, x, y)


def subtract(x, y):
    return _ew("subtract", jnp.subtract, x, y)


def multiply(x, y):
    return _ew("multiply", jnp.multiply, x, y)


def divide(x, y):
    return _ew("divide", jnp.divide, x, y)


def matmul(x, y):
    """sparse @ dense -> dense Tensor
    (reference: python/paddle/sparse/matmul — spmm)."""
    if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        # sparse @ sparse: densify the smaller side (XLA has no spgemm)
        y = y.to_dense()
    yv = _val(y)
    m = x._m if isinstance(x, (SparseCooTensor, SparseCsrTensor)) else None
    if m is None:
        raise TypeError("matmul lhs must be sparse")
    return Tensor(m @ jnp.asarray(yv), stop_gradient=True)


def masked_matmul(x, y, mask):
    """dense @ dense, sampled at ``mask``'s sparsity (SDDMM)
    (reference: paddle.sparse.masked_matmul)."""
    xv, yv = jnp.asarray(_val(x)), jnp.asarray(_val(y))
    mm = _coo(mask)
    rows, cols = mm.indices[:, 0], mm.indices[:, 1]
    vals = jnp.einsum("nk,nk->n", xv[rows, :], yv[:, cols].T)
    return _wrap_like(mask, jsparse.BCOO((vals, mm.indices), shape=mm.shape))


def transpose(x, perm: Sequence[int]):
    m = _coo(x)
    return _wrap_like(x, m.transpose(tuple(perm)))


def coalesce(x):
    return SparseCooTensor(_coo(x).sum_duplicates())


# ----------------------------------------------------------------- unary
def _unary(name, fn):
    def op(x, name_=None):
        m = _coo(x)
        return _wrap_like(x, jsparse.BCOO((fn(m.data), m.indices),
                                          shape=m.shape))

    op.__name__ = name
    return op


# zero-preserving unaries only (the reference restricts to the same set)
relu = _unary("relu", jax.nn.relu)
abs = _unary("abs", jnp.abs)
sin = _unary("sin", jnp.sin)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
atanh = _unary("atanh", jnp.arctanh)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
log1p = _unary("log1p", jnp.log1p)
expm1 = _unary("expm1", jnp.expm1)
neg = _unary("neg", jnp.negative)


def pow(x, factor):
    m = _coo(x)
    return _wrap_like(x, jsparse.BCOO((jnp.power(m.data, factor), m.indices),
                                      shape=m.shape))


def cast(x, dtype):
    from ..core.dtype import to_jax_dtype
    m = _coo(x)
    return _wrap_like(x, jsparse.BCOO((m.data.astype(to_jax_dtype(dtype)),
                                       m.indices), shape=m.shape))


def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    """reference: paddle.sparse.sum — reduce over the dense value."""
    from ..core.dtype import to_jax_dtype
    m = _coo(x)
    dense = m.todense()
    out = jnp.sum(dense, axis=axis, keepdims=keepdim,
                  dtype=to_jax_dtype(dtype))
    return Tensor(out)


rad2deg = _unary("rad2deg", jnp.rad2deg)
deg2rad = _unary("deg2rad", jnp.deg2rad)


def mv(x, vec):
    """reference: paddle.sparse.mv — sparse matrix x dense vector."""
    from ..core.tensor import _val
    m = _coo(x)
    return Tensor(m @ _val(vec))


def reshape(x, shape, name=None):
    """reference: paddle.sparse.reshape (via dense round-trip — BCOO
    reshape support is shape-limited)."""
    m = _coo(x)
    dense = m.todense().reshape(tuple(shape))
    return _wrap_like(x, jsparse.BCOO.fromdense(dense))


__all__ += ["rad2deg", "deg2rad", "mv", "reshape", "sum"]

from . import nn  # noqa: E402,F401
