"""Process-wide metrics registry: counters, gauges, histograms.

Design constraints (the serving/train hot paths dictate them):

  - **Host-side only.** Telemetry never executes under trace — a write
    inside a jitted body would either fail on tracers or fire once at
    trace time and silently freeze. tracecheck rule TRC007 enforces
    this statically (and requires an explicit pragma + reason for any
    write in ``# tracecheck: hotpath`` code).
  - **Near-zero overhead.** Instrument handles are resolved ONCE at
    construction time (``registry().counter(...)``) and pre-bound on
    the instrumented object; a hot-path write is one attribute read
    plus a float add / list-index bump — no registry lookup, no lock,
    no flag read per call. With ``FLAGS_telemetry=0`` the construction
    site binds the shared :data:`NULL` stub instead, so the hot path
    pays one no-op method call and nothing else.
  - **Exportable.** :meth:`MetricsRegistry.snapshot` returns a pure
    JSON-able dict (the format ``BENCH_*.json`` artifacts embed);
    :func:`~paddle_tpu.observability.export.to_prometheus` renders the
    same snapshot as Prometheus text exposition format.

Counter/gauge writes are plain ``+=`` under the GIL: single bytecode
races could in principle drop an increment under heavy threading, which
is the standard statsd trade — telemetry must never add a lock to the
path it observes. Snapshots take the registry lock only to list the
families, never to read values.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "NULL",
    "exponential_buckets", "LATENCY_BUCKETS", "registry",
    "series_quantile",
]


def exponential_buckets(start: float, factor: float, count: int
                        ) -> Tuple[float, ...]:
    """``count`` fixed exponential bucket upper bounds: start, start *
    factor, ... — the histogram layout (one +Inf overflow bucket rides
    implicitly at the end)."""
    if start <= 0 or factor <= 1.0 or count < 1:
        raise ValueError("need start > 0, factor > 1, count >= 1")
    out: List[float] = []
    v = float(start)
    for _ in range(count):
        out.append(v)
        v *= factor
    return tuple(out)


# 100 µs .. ~105 s in x2 steps: one ladder covers inter-token latency
# (~ms), TTFT (~10ms-1s), compile walls (~s) and epoch syncs.
LATENCY_BUCKETS = exponential_buckets(1e-4, 2.0, 21)


class Counter:
    """Monotonic counter. ``inc`` is one float add — no lock."""

    __slots__ = ("_value",)
    kind = "counter"

    def __init__(self):
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Last-write-wins instantaneous value (queue depth, occupancy)."""

    __slots__ = ("_value",)
    kind = "gauge"

    def __init__(self):
        self._value = 0.0

    def set(self, v: float) -> None:
        self._value = v

    def inc(self, n: float = 1.0) -> None:
        self._value += n

    def dec(self, n: float = 1.0) -> None:
        self._value -= n

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Fixed-exponential-bucket histogram; ``observe`` is one bisect +
    two adds. Tracks sum/count/min/max so snapshot quantile estimates
    can clamp to the observed range."""

    __slots__ = ("_uppers", "_counts", "_sum", "_count", "_min", "_max")
    kind = "histogram"

    def __init__(self, buckets: Sequence[float] = LATENCY_BUCKETS):
        self._uppers = tuple(sorted(float(b) for b in buckets))
        if not self._uppers:
            raise ValueError("histogram needs at least one bucket")
        self._counts = [0] * (len(self._uppers) + 1)   # +1: overflow
        self._sum = 0.0
        self._count = 0
        self._min = float("inf")
        self._max = float("-inf")

    def observe(self, v: float) -> None:
        self._counts[bisect.bisect_left(self._uppers, v)] += 1
        self._sum += v
        self._count += 1
        if v < self._min:
            self._min = v
        if v > self._max:
            self._max = v

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def quantile(self, q: float) -> Optional[float]:
        return series_quantile(self._series_entry({}), q)

    def _series_entry(self, labels: Dict[str, str]) -> Dict[str, Any]:
        return {
            "labels": labels, "count": self._count, "sum": self._sum,
            "min": (self._min if self._count else None),
            "max": (self._max if self._count else None),
            "buckets": list(self._uppers), "counts": list(self._counts),
        }


def series_quantile(entry: Dict[str, Any], q: float) -> Optional[float]:
    """q-quantile estimate from a snapshot histogram series entry:
    linear interpolation within the hit bucket, clamped to the observed
    min/max (so a p50 of four sub-bucket samples never reports below
    the smallest one seen). Works on round-tripped JSON."""
    count = entry.get("count", 0)
    if not count:
        return None
    target = q * count
    cum = 0.0
    lower = 0.0
    for upper, c in zip(entry["buckets"], entry["counts"]):
        if c and cum + c >= target:
            v = lower + (target - cum) / c * (upper - lower)
            break
        cum += c
        lower = upper
    else:
        v = entry["max"] if entry.get("max") is not None else lower
    mn, mx = entry.get("min"), entry.get("max")
    if mn is not None:
        v = max(v, mn)
    if mx is not None:
        v = min(v, mx)
    return v


class _NullInstrument:
    """Shared no-op stub every instrument kind collapses to when
    ``FLAGS_telemetry`` is off: construction sites bind this once and
    the hot path pays a single no-op method call."""

    __slots__ = ()

    def inc(self, n: float = 1.0) -> None:
        pass

    def dec(self, n: float = 1.0) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass

    def labels(self, **kv) -> "_NullInstrument":
        return self

    def quantile(self, q: float) -> None:
        return None

    @property
    def value(self) -> float:
        return 0.0

    @property
    def count(self) -> int:
        return 0


NULL = _NullInstrument()


class _Family:
    """One registered metric name: kind + help + label schema + the
    children (one instrument per label-value tuple)."""

    __slots__ = ("name", "help", "kind", "labelnames", "buckets",
                 "_make", "_children", "_lock")

    def __init__(self, name, help, kind, labelnames, make, buckets=None):
        self.name = name
        self.help = help
        self.kind = kind
        self.labelnames = tuple(labelnames)
        self.buckets = buckets          # histogram layout (None otherwise)
        self._make = make
        self._children: Dict[Tuple[str, ...], Any] = {}
        self._lock = threading.Lock()

    def labels(self, **kv):
        """The child instrument for one label-value combination —
        resolve ONCE and keep the handle; this path takes a lock."""
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name!r} has labels {self.labelnames}, "
                f"got {tuple(sorted(kv))}")
        key = tuple(str(kv[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._make()
            return child

    def series(self) -> Iterable[Tuple[Dict[str, str], Any]]:
        with self._lock:
            items = list(self._children.items())
        for key, child in items:
            yield dict(zip(self.labelnames, key)), child


class MetricsRegistry:
    """Named, labeled instrument registry. ``counter``/``gauge``/
    ``histogram`` are idempotent: the same name returns the same family
    (kind and label schema must match), so every engine/step instance
    in the process shares one series set."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _get(self, name, help, kind, labelnames, make, buckets=None):
        labelnames = tuple(labelnames)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(name, help, kind, labelnames, make, buckets)
                self._families[name] = fam
            elif fam.kind != kind or fam.labelnames != labelnames \
                    or fam.buckets != buckets:
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind} "
                    f"with labels {fam.labelnames}"
                    + (f" and buckets {fam.buckets}" if fam.buckets else "")
                    + f"; requested {kind} with {labelnames}"
                    + (f" and buckets {buckets}" if buckets else ""))
        if not labelnames:
            return fam.labels()        # unlabeled: hand out the child
        return fam

    def counter(self, name: str, help: str = "", labels: Sequence[str] = ()):
        return self._get(name, help, "counter", labels, Counter)

    def gauge(self, name: str, help: str = "", labels: Sequence[str] = ()):
        return self._get(name, help, "gauge", labels, Gauge)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None):
        b = tuple(buckets) if buckets is not None else LATENCY_BUCKETS
        return self._get(name, help, "histogram", labels,
                         lambda: Histogram(b), buckets=b)

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able point-in-time view of every series. Counters and
        gauges carry ``value``; histograms carry count/sum/min/max plus
        the bucket bounds and per-bucket counts (p50/p99 derivable via
        :func:`series_quantile`)."""
        with self._lock:
            fams = list(self._families.values())
        metrics: Dict[str, Any] = {}
        for fam in fams:
            series = []
            for lbl, child in fam.series():
                if fam.kind == "histogram":
                    series.append(child._series_entry(lbl))
                else:
                    series.append({"labels": lbl, "value": child.value})
            metrics[fam.name] = {"type": fam.kind, "help": fam.help,
                                 "series": series}
        return {"ts": time.time(), "metrics": metrics}

    def clear(self) -> None:
        """Drop every family (tests; a fresh process view). Handles
        bound before the clear keep writing to orphaned instruments —
        re-resolve after clearing."""
        with self._lock:
            self._families.clear()


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-wide registry."""
    return _REGISTRY
