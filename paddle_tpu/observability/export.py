"""Exporters over the registry snapshot / span ring.

Two wire formats, both derived from the same JSON-able snapshot dict so
an embedded ``BENCH_*.json`` telemetry blob and a live registry render
identically:

  - :func:`to_prometheus` — Prometheus text exposition format
    (cumulative ``_bucket{le=...}`` histogram encoding);
  - :func:`chrome_trace` / :func:`save_chrome_trace` — the span ring as
    a Chrome-trace/Perfetto JSON object.
"""

from __future__ import annotations

import json
import math
from typing import Any, Dict, Iterable, Optional

from .metrics import registry
from .tracing import tracer

__all__ = ["to_prometheus", "chrome_trace", "save_chrome_trace",
           "save_snapshot"]


def _fmt_labels(labels: Dict[str, str], extra=()) -> str:
    pairs = [(k, str(v)) for k, v in sorted(labels.items())] + list(extra)
    if not pairs:
        return ""
    body = ",".join(f'{k}="{_escape(v)}"' for k, v in pairs)
    return "{" + body + "}"


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_val(v: float) -> str:
    if v != v:                                  # NaN
        return "NaN"
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    return repr(float(v)) if v != int(v) else str(int(v))


def to_prometheus(snapshot: Optional[Dict[str, Any]] = None) -> str:
    """Render a registry snapshot (default: the live process registry)
    as Prometheus text exposition format."""
    if snapshot is None:
        snapshot = registry().snapshot()
    lines = []
    for name, fam in sorted(snapshot.get("metrics", {}).items()):
        if fam.get("help"):
            lines.append(f"# HELP {name} {fam['help']}")
        lines.append(f"# TYPE {name} {fam['type']}")
        for s in fam.get("series", []):
            labels = s.get("labels", {})
            if fam["type"] == "histogram":
                cum = 0
                for upper, c in zip(s["buckets"], s["counts"]):
                    cum += c
                    lines.append(
                        f"{name}_bucket"
                        f"{_fmt_labels(labels, [('le', _fmt_val(upper))])}"
                        f" {cum}")
                cum += s["counts"][len(s["buckets"])]
                lines.append(
                    f"{name}_bucket{_fmt_labels(labels, [('le', '+Inf')])}"
                    f" {cum}")
                lines.append(
                    f"{name}_sum{_fmt_labels(labels)} {_fmt_val(s['sum'])}")
                lines.append(
                    f"{name}_count{_fmt_labels(labels)} {s['count']}")
            else:
                lines.append(
                    f"{name}{_fmt_labels(labels)} {_fmt_val(s['value'])}")
    return "\n".join(lines) + ("\n" if lines else "")


def chrome_trace(events: Optional[Iterable[Dict[str, Any]]] = None
                 ) -> Dict[str, Any]:
    """Chrome-trace JSON object for ``events`` (default: the live span
    ring)."""
    if events is None:
        return tracer().chrome_trace()
    return {"traceEvents": list(events), "displayTimeUnit": "ms"}


def save_chrome_trace(path: str,
                      events: Optional[Iterable[Dict[str, Any]]] = None
                      ) -> None:
    with open(path, "w") as fh:
        json.dump(chrome_trace(events), fh)


def save_snapshot(path: str,
                  snapshot: Optional[Dict[str, Any]] = None) -> None:
    with open(path, "w") as fh:
        json.dump(snapshot if snapshot is not None
                  else registry().snapshot(), fh, indent=1)
