"""Runtime telemetry: unified metrics registry + span tracing.

The signal layer every serving/perf claim stands on: a process-wide
:mod:`metrics <paddle_tpu.observability.metrics>` registry (counters,
gauges, fixed-exponential-bucket histograms; JSON snapshot + Prometheus
text) and a :mod:`span tracer <paddle_tpu.observability.tracing>`
(nested host-side timing events -> Chrome-trace JSON, mirrored into
``jax.profiler`` captures).

Instrumented subsystems: ``generation.serving.ServingEngine`` (request
lifecycle spans, TTFT/inter-token histograms, queue/occupancy/KV-pool
gauges, prefix-cache counters), ``hapi.train_step.TrainStep`` (in-flight
window depth, sync/throttle/retrace counters, pull/sync spans),
``generation.program_cache`` (hit/miss counters, compile wall-time
histograms) and ``io.DevicePrefetcher``. ``tools/telemetry_dump.py``
renders snapshots; ``bench.py`` and the ``tools/*_bench.py`` drivers
embed a snapshot in their ``BENCH_*.json`` output.

Everything is gated behind ``FLAGS_telemetry`` (default on). The
contract is HOST-SIDE ONLY: a telemetry write must never be reachable
under trace (it would fire once at trace time and freeze, or fail on a
tracer) — tracecheck rule TRC007 enforces this, and additionally
requires an explicit pragma + reason for writes in declared
``# tracecheck: hotpath`` code.

Usage::

    from paddle_tpu import observability as obs

    reqs = obs.registry().counter("my_requests", "requests seen")
    lat = obs.registry().histogram("my_latency_seconds")
    with obs.span("handle", rid=7):
        ...
        lat.observe(dt)
    obs.registry().snapshot()          # JSON-able dict
    obs.to_prometheus()                # text exposition format
    obs.tracer().save("trace.json")    # open in chrome://tracing
"""

from __future__ import annotations

from .metrics import (Counter, Gauge, Histogram, LATENCY_BUCKETS,
                      MetricsRegistry, NULL, exponential_buckets, registry,
                      series_quantile)
from .tracing import (NULL_SPAN, Span, SpanTracer, null_counter, null_event,
                      null_span, tracer)
from .export import (chrome_trace, save_chrome_trace, save_snapshot,
                     to_prometheus)
from . import memory

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "NULL",
    "LATENCY_BUCKETS", "exponential_buckets", "registry",
    "series_quantile", "Span", "SpanTracer", "NULL_SPAN", "tracer",
    "null_span", "null_event", "null_counter", "chrome_trace",
    "save_chrome_trace", "save_snapshot", "to_prometheus", "enabled",
    "span", "snapshot", "memory",
]


def enabled() -> bool:
    """Resolve ``FLAGS_telemetry``. Call at CONSTRUCTION time and bind
    either real instruments or the ``NULL``/``null_span`` stubs — never
    per hot-path call (instrumented objects keep whichever binding they
    were built under; rebuild after toggling the flag)."""
    from .. import flags
    return bool(flags.get_flag("telemetry"))


def span(name: str, **args):
    """Convenience scoped span honoring ``FLAGS_telemetry`` per call —
    for warm paths (epoch boundaries, loaders). Hot paths pre-bind
    ``tracer().span`` instead."""
    if not enabled():
        return NULL_SPAN
    return tracer().span(name, **args)


def snapshot():
    """The live registry snapshot (JSON-able)."""
    return registry().snapshot()
