"""Span tracer: nested host-side timing events -> Chrome-trace JSON.

``tracer().span("prefill", rid=3)`` is a context manager (and
decorator) that records one complete event — name, wall-clock begin,
duration, thread — into a bounded ring buffer. The export is the Chrome
``traceEvents`` format (``chrome://tracing`` / Perfetto opens it
directly), so a serving run under load produces a per-request timeline
with zero external dependencies.

Interop with the profiler facade: every span also enters a
``jax.profiler.TraceAnnotation`` (the primitive behind
``paddle_tpu.profiler.RecordEvent``), so when a ``jax.profiler`` device
capture is active the same spans land inside the XPlane trace alongside
the XLA events. The reverse direction holds too:
``profiler.RecordEvent`` scopes are mirrored into this ring buffer.

Host-side only, like the metrics registry — a span entered under trace
would time the TRACE, not the execution, and is flagged by tracecheck
rule TRC007.
"""

from __future__ import annotations

import functools
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = ["SpanTracer", "Span", "NULL_SPAN", "tracer", "null_span",
           "null_event", "null_counter"]

try:                                    # the annotation is optional:
    import jax                          # pure-host tools can trace spans
    _ANNOTATION = jax.profiler.TraceAnnotation
except Exception:                       # pragma: no cover - import guard
    _ANNOTATION = None

try:
    # jax 0.4.x internal: the live profiler session. Entering a
    # TraceAnnotation costs ~10 µs per span on the decode hot path;
    # outside a capture it annotates nothing, so spans skip it unless a
    # session is actually recording. Private API — on any drift we fall
    # back to always annotating (correct, just slower under load).
    from jax._src.profiler import _profile_state as _JAX_PROFILE_STATE
except Exception:                       # pragma: no cover - version drift
    _JAX_PROFILE_STATE = None


def _capture_active() -> bool:
    if _JAX_PROFILE_STATE is None:
        return True                     # can't tell: keep annotations
    try:
        return _JAX_PROFILE_STATE.profile_session is not None
    except Exception:                   # pragma: no cover - state drift
        return True


class Span:
    """One timed scope. Context manager; also usable as a decorator
    (``@tracer().span("load")`` — note the enabled/disabled decision is
    then frozen at decoration time; prefer the ``with`` form for code
    whose telemetry flag may toggle)."""

    __slots__ = ("_tracer", "name", "args", "_t0", "_ann")

    def __init__(self, tr: "SpanTracer", name: str,
                 args: Optional[Dict[str, Any]] = None):
        self._tracer = tr
        self.name = name
        self.args = args or {}
        self._t0 = 0.0
        self._ann = None

    def __enter__(self) -> "Span":
        if _ANNOTATION is not None and _capture_active():
            try:
                self._ann = _ANNOTATION(self.name)
                self._ann.__enter__()
            except Exception:           # annotation is best-effort
                self._ann = None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        t1 = time.perf_counter()
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None
        self._tracer._append(self.name, self._t0, t1, self.args)
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*a, **kw):
            with Span(self._tracer, self.name, self.args):
                return fn(*a, **kw)
        return wrapper


class _NullSpan:
    """No-op stand-in bound when telemetry is off."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __call__(self, fn):
        return fn


NULL_SPAN = _NullSpan()


def null_span(name: str, **args) -> _NullSpan:
    return NULL_SPAN


def null_event(name: str, t0: float, t1: float, **args) -> None:
    return None


def null_counter(name: str, t: float, **values) -> None:
    return None


class SpanTracer:
    """Bounded ring buffer of complete events (Chrome-trace ``"X"``
    phase). Appends are deque ops under the GIL — no lock on the record
    path; ``events()``/exports copy."""

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            from .. import flags
            capacity = int(flags.get_flag("telemetry_ring"))
        self._events: deque = deque(maxlen=max(1, capacity))
        self._pid = os.getpid()

    # ------------------------------------------------------------ record
    def span(self, name: str, **args) -> Span:
        return Span(self, name, args)

    def event(self, name: str, t0: float, t1: float, **args) -> None:
        """Retroactive complete event from explicit ``perf_counter``
        begin/end stamps (request lifecycle phases whose boundaries were
        observed before the phase name was known)."""
        self._append(name, t0, t1, args)

    def counter(self, name: str, t: float, **values) -> None:
        """Perfetto counter sample (Chrome-trace ``"C"`` phase): each
        key of ``values`` renders as its own counter track aligned with
        the span timeline — how pool bytes/pages-in-use line up against
        the serving steps in one view. One deque append, like spans."""
        self._events.append({
            "name": name, "ph": "C",
            "ts": t * 1e6,
            "pid": self._pid, "tid": threading.get_ident(),
            "args": {k: float(v) for k, v in values.items()},
        })

    def _append(self, name, t0, t1, args) -> None:
        self._events.append({
            "name": name, "ph": "X",
            "ts": t0 * 1e6,                       # Chrome wants µs
            "dur": max(0.0, (t1 - t0)) * 1e6,
            "pid": self._pid, "tid": threading.get_ident(),
            "args": dict(args),
        })

    # ------------------------------------------------------------ export
    def events(self) -> List[Dict[str, Any]]:
        return list(self._events)

    def chrome_trace(self) -> Dict[str, Any]:
        """The ring as a Chrome-trace/Perfetto JSON object."""
        return {"traceEvents": self.events(), "displayTimeUnit": "ms"}

    def save(self, path: str) -> None:
        import json
        with open(path, "w") as fh:
            json.dump(self.chrome_trace(), fh)

    def clear(self) -> None:
        self._events.clear()

    def __len__(self) -> int:
        return len(self._events)


_TRACER: Optional[SpanTracer] = None
_TRACER_LOCK = threading.Lock()


def tracer() -> SpanTracer:
    """The process-wide span tracer (ring size from
    ``FLAGS_telemetry_ring`` at first use)."""
    global _TRACER
    if _TRACER is None:
        with _TRACER_LOCK:
            if _TRACER is None:
                _TRACER = SpanTracer()
    return _TRACER
