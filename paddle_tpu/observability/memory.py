"""memwatch: the HBM/memory observatory — third pillar beside
:mod:`metrics` and :mod:`tracing`.

Answers "where does *memory* go" the way r09 answered "where does time
go", with three instruments sharing one accounting vocabulary:

  1. **Compiled-program capture** — every program admitted by the decode
     program cache and every jitted ``TrainStep`` records its XLA
     ``CompiledMemoryStats`` (argument / output / temp / alias /
     generated-code bytes, plus the derived peak) into the registry as
     ``program_memory_bytes{kind,bucket,extra,section}`` gauges and a
     host-side row table (:func:`program_table`). Capture costs ONE
     duplicate ``lower().compile()`` per (re)trace — XLA's buffer
     assignment is the only source of truth for temp/peak, and this
     jaxlib exposes no handle to the executable the jit dispatch itself
     built. The cost lands exactly where r09's compile-seconds histogram
     already charges retraces; ``FLAGS_memwatch=0`` drops it while
     keeping the rest of telemetry.
  2. **Live pool ledger** — the serving engine publishes its
     :class:`~paddle_tpu.kernels.paged_attention.PagedKVCache` ledger
     (pages/bytes used, free, shared, pinned; free-list fragmentation)
     as step-end gauges plus a Perfetto counter track, and
     :func:`sample_device_memory` banks backend watermarks
     (``device.memory_stats()`` where the PJRT backend supports it;
     host peak RSS always).
  3. **Analytic estimator / what-if planner** — :func:`estimate_program`
     and :func:`estimate_engine_memory` predict the same sections from
     avals + pool geometry + model dims WITHOUT compiling, for
     configurations too big to build locally ("does 7B int8 + page
     budget P + rung 32 fit in 16 GB?"). Validated against
     ``CompiledMemoryStats`` on tier-1-sized programs
     (tests/test_memwatch.py asserts temp+output within 10%).

Gating follows the r09 contract exactly: everything is host-side (the
capture itself runs at trace time, never under trace), rides
``FLAGS_telemetry`` (off = the null-stub binding, zero residue), and
``FLAGS_memwatch`` additionally gates the duplicate-compile capture.
Neither flag is in ``PROGRAM_FLAGS`` — toggling them never recompiles a
serving or train program.
"""

from __future__ import annotations

import sys
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "enabled", "stats_from_compiled", "capture_jitted", "capture_program",
    "record_program", "program_table", "clear_program_table",
    "sample_device_memory", "section",
    "estimate_program", "estimate_decode_program", "estimate_prefill_program",
    "estimate_engine_memory", "fits", "sharded_param_bytes",
    "compare_program_rows", "PoolGeometry", "ModelDims", "weight_bytes",
    "aval_bytes", "MEMWATCH_SCHEMA",
]

MEMWATCH_SCHEMA = 1

# the CompiledMemoryStats sections every surface (gauges, table rows,
# banked artifacts, estimator output) agrees on
SECTIONS = ("argument", "output", "temp", "alias", "generated_code", "peak")

_TABLE: Dict[Tuple[str, str, int, str], Dict[str, Any]] = {}
_TABLE_LOCK = threading.Lock()


def enabled() -> bool:
    """Memwatch capture gate: ``FLAGS_telemetry`` AND ``FLAGS_memwatch``.
    Resolve at CONSTRUCTION time like every observability binding."""
    from .. import flags
    return bool(flags.get_flag("telemetry")) and \
        bool(flags.get_flag("memwatch"))


# --------------------------------------------------------------- capture
def stats_from_compiled(compiled) -> Dict[str, int]:
    """The section dict for one compiled executable (``jax.stages
    .Compiled`` or anything exposing ``memory_analysis()``). ``peak`` is
    derived: arguments + outputs - aliased (donation) + temp + code —
    the resident HBM high-water of one dispatch."""
    ma = compiled.memory_analysis() if hasattr(compiled, "memory_analysis") \
        else compiled
    out = {
        "argument": int(ma.argument_size_in_bytes),
        "output": int(ma.output_size_in_bytes),
        "temp": int(ma.temp_size_in_bytes),
        "alias": int(ma.alias_size_in_bytes),
        "generated_code": int(ma.generated_code_size_in_bytes),
    }
    out["peak"] = (out["argument"] + out["output"] - out["alias"]
                   + out["temp"] + out["generated_code"])
    return out


def capture_jitted(fn, args: Sequence[Any],
                   kwargs: Optional[Dict[str, Any]] = None
                   ) -> Optional[Dict[str, int]]:
    """AOT lower+compile ``fn`` (a jitted callable) at ``args``' avals
    and return the section dict, or None when the backend/lowering
    refuses (abstract avals survive donation, so this works even after
    the dispatch consumed the donated buffers)."""
    try:
        compiled = fn.lower(*args, **(kwargs or {})).compile()
        return stats_from_compiled(compiled)
    except Exception:
        return None


def record_program(kind: str, bucket: int, stats: Dict[str, int],
                   extra: Any = (), model: str = "") -> None:
    """Bank one program's section dict: registry gauges
    ``program_memory_bytes{model,kind,bucket,extra,section}`` (last
    write wins, the gauge contract) plus the host-side row table the
    benches and the regression gate read. ``model`` disambiguates
    same-shaped programs of different models sharing the process (the
    program cache passes a model-signature prefix, TrainStep the model
    class name)."""
    from .metrics import registry
    ex = _extra_str(extra)
    fam = registry().gauge(
        "program_memory_bytes",
        "XLA CompiledMemoryStats of cached compiled programs, by "
        "section (peak = argument + output - alias + temp + code)",
        labels=("model", "kind", "bucket", "extra", "section"))
    for sec in SECTIONS:
        fam.labels(model=model, kind=kind, bucket=str(bucket), extra=ex,
                   section=sec).set(float(stats.get(sec, 0)))
    with _TABLE_LOCK:
        row = _TABLE.setdefault((model, kind, int(bucket), ex), {
            "model": model, "kind": kind, "bucket": int(bucket),
            "extra": ex, "captures": 0})
        row.update({sec: int(stats.get(sec, 0)) for sec in SECTIONS})
        row["captures"] += 1


def capture_program(kind: str, bucket: int, extra: Any, fn,
                    args: Sequence[Any],
                    kwargs: Optional[Dict[str, Any]] = None,
                    model: str = "") -> bool:
    """Capture + record one cached program (the program-cache /
    TrainStep hook). Failures are counted, never raised — memory
    accounting must not take down a dispatch that already succeeded."""
    stats = capture_jitted(fn, args, kwargs)
    if stats is None:
        from .metrics import registry
        registry().counter(
            "memwatch_capture_failures",
            "compiled-memory captures the backend refused",
            labels=("kind",)).labels(kind=kind).inc()
        return False
    record_program(kind, bucket, stats, extra, model=model)
    return True


def program_table() -> List[Dict[str, Any]]:
    """Every captured program's row (sorted, JSON-able) — the artifact
    the benches embed and ``MEMWATCH_*.json`` banks."""
    with _TABLE_LOCK:
        rows = [dict(r) for r in _TABLE.values()]
    return sorted(rows, key=lambda r: (r["model"], r["kind"], r["bucket"],
                                       r["extra"]))


def clear_program_table() -> None:
    with _TABLE_LOCK:
        _TABLE.clear()


TABLE_COLUMNS = ("model", "kind", "bucket", "extra", "argument", "output",
                 "temp", "alias", "peak")


def format_program_table(rows: Sequence[Dict[str, Any]]) -> str:
    """Render program rows as the fixed-width table every CLI view
    shares (``tools/memwatch.py view``, ``tools/telemetry_dump.py
    --memory``) — one renderer, like one accounting path."""
    lines = ["  ".join(f"{h:>14s}" for h in TABLE_COLUMNS)]
    for r in rows:
        lines.append("  ".join(f"{str(r.get(h, '')):>14s}"
                               for h in TABLE_COLUMNS))
    return "\n".join(lines)


def _extra_str(extra: Any) -> str:
    if extra in ((), None, ""):
        return ""
    if isinstance(extra, (tuple, list)):
        return ",".join(str(e) for e in extra)
    return str(extra)


# ---------------------------------------------------- device watermarks
def sample_device_memory(publish: bool = True) -> Dict[str, Any]:
    """Backend memory watermarks where the PJRT backend exposes them
    (``device.memory_stats()`` — TPU/GPU report bytes_in_use /
    peak_bytes_in_use / bytes_limit; CPU returns None), plus the host
    process peak RSS. Publishes ``device_memory_bytes{device,stat}`` /
    ``host_memory_bytes{stat}`` gauges when telemetry is on and returns
    the raw JSON-able sample either way."""
    out: Dict[str, Any] = {"devices": {}, "host": {}}
    try:
        import jax
        for d in jax.local_devices():
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if stats:
                out["devices"][str(d.id)] = {
                    k: int(v) for k, v in stats.items()
                    if isinstance(v, (int, float))}
    except Exception:
        pass
    try:
        import resource
        ru = resource.getrusage(resource.RUSAGE_SELF)
        # linux reports ru_maxrss in KiB; darwin reports bytes
        scale = 1 if sys.platform == "darwin" else 1024
        out["host"]["peak_rss"] = int(ru.ru_maxrss) * scale
    except Exception:
        pass
    if publish:
        from . import enabled as _telemetry_on
        if _telemetry_on():
            from .metrics import registry
            r = registry()
            if out["devices"]:
                fam = r.gauge("device_memory_bytes",
                              "PJRT device memory watermarks "
                              "(device.memory_stats())",
                              labels=("device", "stat"))
                for dev, stats in out["devices"].items():
                    for k, v in stats.items():
                        fam.labels(device=dev, stat=k).set(float(v))
            if out["host"]:
                fam = r.gauge("host_memory_bytes",
                              "host process memory watermarks",
                              labels=("stat",))
                for k, v in out["host"].items():
                    fam.labels(stat=k).set(float(v))
    return out


def section() -> Dict[str, Any]:
    """The ``"memory"`` section benches embed next to ``"telemetry"``:
    the captured program table + device/host watermarks. (The live pool
    ledger and the per-program gauges already ride the telemetry
    snapshot itself.)"""
    return {"schema": MEMWATCH_SCHEMA,
            "programs": program_table(),
            "watermarks": sample_device_memory()}


# ------------------------------------------------------------ estimator
# The analytic twin of stats_from_compiled: predict the same sections
# from avals + geometry WITHOUT compiling. Exact for arguments/outputs/
# alias (those are just the avals); temp is a calibrated working-set
# model (XLA's buffer assignment reuses aggressively, so temp is a
# max-live, not a sum of intermediates). Calibration constants below
# were fit against CompiledMemoryStats on the tier-1 CPU programs and
# are validated to the 10% temp+output bar in tests/test_memwatch.py.

_DECODE_TEMP_K = 1.25     # decode: full working-set chain stays live-ish
_PREFILL_TEMP_K = 1.0     # prefill/chunk: two largest stage buffers


def aval_bytes(x) -> int:
    """Bytes of one array-like / ShapeDtypeStruct / (shape, dtype)."""
    if isinstance(x, tuple) and len(x) == 2:
        shape, dtype = x
    else:
        shape, dtype = x.shape, x.dtype
    return int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize


def estimate_program(arg_avals: Sequence[Any], out_avals: Sequence[Any],
                     donated: Sequence[int] = (),
                     temp: int = 0, generated_code: int = 0
                     ) -> Dict[str, int]:
    """Generic donation-aware section estimate from flat aval lists:
    ``donated`` indexes into ``arg_avals``; those bytes alias outputs
    instead of doubling the peak."""
    arg = sum(aval_bytes(a) for a in arg_avals)
    out = sum(aval_bytes(a) for a in out_avals)
    alias = sum(aval_bytes(arg_avals[i]) for i in donated)
    est = {"argument": arg, "output": out, "temp": int(temp),
           "alias": alias, "generated_code": int(generated_code)}
    est["peak"] = arg + out - alias + est["temp"] + est["generated_code"]
    return est


class PoolGeometry:
    """The KV pool shape vocabulary every estimate walks: mirrors
    :class:`PagedKVCache`'s constructor args."""

    __slots__ = ("num_layers", "num_pages", "page_size", "num_kv_heads",
                 "head_dim", "max_pages_per_seq", "dtype", "kv_quant")

    def __init__(self, num_layers: int, num_pages: int, page_size: int,
                 num_kv_heads: int, head_dim: int, max_pages_per_seq: int,
                 dtype: Any = "float32", kv_quant: bool = False):
        self.num_layers = int(num_layers)
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.num_kv_heads = int(num_kv_heads)
        self.head_dim = int(head_dim)
        self.max_pages_per_seq = int(max_pages_per_seq)
        self.dtype = np.dtype(dtype) if not hasattr(dtype, "itemsize") \
            else dtype
        # int8-quantized pool: int8 payload + one f32 amax scale per
        # (head, page, token) row alongside (r18)
        self.kv_quant = bool(kv_quant)

    @classmethod
    def of_pool(cls, pool) -> "PoolGeometry":
        """Geometry of a live :class:`PagedKVCache`."""
        from ..kernels.paged_attention import QuantizedPages
        k0 = pool.k_pages[0]
        quant = isinstance(k0, QuantizedPages)
        hkv, num_pages, page, d = k0.shape
        return cls(len(pool.k_pages), num_pages, page, hkv, d,
                   pool.max_pages_per_seq,
                   k0.q.dtype if quant else k0.dtype, kv_quant=quant)

    def pool_bytes(self) -> int:
        """Both pools, all layers — the donated/aliased block. A
        quantized pool bills the int8 payload plus the f32 per-token
        scale rows (head_dim + 4 bytes per token-head)."""
        per_elem = (self.head_dim * np.dtype(self.dtype).itemsize
                    + (4 if self.kv_quant else 0))
        return (self.num_layers * 2 * self.num_kv_heads * self.num_pages
                * self.page_size * per_elem)

    def tables_bytes(self, batch: int) -> int:
        """block table + seq_lens for one dispatch (int32)."""
        return batch * (self.max_pages_per_seq + 1) * 4

    @property
    def max_seq(self) -> int:
        return self.max_pages_per_seq * self.page_size


class ModelDims:
    """The model dims the temp model needs — constructable from any
    config exposing the Llama/GPT field names, or from explicit kwargs
    (the planner's too-big-to-build path)."""

    __slots__ = ("hidden", "layers", "heads", "kv_heads", "intermediate",
                 "vocab", "param_count")

    def __init__(self, hidden: int, layers: int, heads: int,
                 kv_heads: Optional[int], intermediate: int, vocab: int,
                 param_count: Optional[int] = None):
        self.hidden = int(hidden)
        self.layers = int(layers)
        self.heads = int(heads)
        self.kv_heads = int(kv_heads if kv_heads else heads)
        self.intermediate = int(intermediate)
        self.vocab = int(vocab)
        self.param_count = param_count

    @classmethod
    def of_config(cls, cfg) -> "ModelDims":
        inter = getattr(cfg, "intermediate_size", None)
        if inter is None:                      # GPT publishes a 4x MLP
            inter = 4 * cfg.hidden_size
        n = cfg.num_params() if hasattr(cfg, "num_params") else None
        return cls(cfg.hidden_size, cfg.num_hidden_layers,
                   cfg.num_attention_heads,
                   getattr(cfg, "num_key_value_heads", None),
                   inter, cfg.vocab_size, n)

    @property
    def head_dim(self) -> int:
        return self.hidden // self.heads

    @property
    def kv_dim(self) -> int:
        return self.kv_heads * self.head_dim


def _decode_temp(dims: ModelDims, geom: PoolGeometry, batch: int) -> int:
    """Decode-step temp model: per-row working set of one layer chain
    (x/qkv round-trips, attention scores over the gathered width, FFN)
    summed over layers, plus the logits row — all f32 (kernels
    accumulate in f32), scaled by the calibrated live-set factor."""
    per_layer = (4 * dims.hidden            # x, q, attn-out, residual
                 + 2 * dims.kv_dim          # k, v new-token rows
                 + dims.heads * geom.max_seq   # attention scores
                 + 2 * dims.intermediate)   # gate/up FFN halves
    elems = batch * (dims.layers * per_layer + dims.vocab)
    return int(_DECODE_TEMP_K * elems * 4)


def _prefill_temp(dims: ModelDims, geom: PoolGeometry, s: int,
                  chunked: bool = False) -> int:
    """Prefill/chunk temp model (b=1, S query tokens): XLA's buffer
    reuse keeps roughly the two largest stage buffers live at the
    worst program point — scores, the FFN intermediate, the logits
    block, or the QKV block.

    ``chunked`` selects the r17 copy-free chunk path: attention reads
    K/V pages through the block table (a fixed-size page-GROUP block in
    flight — ~128 keys on the XLA twin, one page on the pallas kernel —
    online softmax), so the gathered full-context K/V view and the full
    S x max_seq score matrix never materialize — their stages are
    replaced by the page-group score/K/V blocks and the softmax carry."""
    if chunked:
        # mirrors _CHUNK_GROUP_KEYS in kernels/paged_attention.py: the
        # XLA twin batches pages into ~128-key groups per loop step
        pages = -(-geom.max_seq // geom.page_size)
        gk = min(pages, max(1, 128 // geom.page_size)) * geom.page_size
        stages = [
            dims.heads * s * gk,                 # page-group score block
            2 * gk * dims.kv_dim,                # gathered K+V group block
            2 * dims.heads * s * dims.head_dim,  # online-softmax acc carry
            2 * s * dims.intermediate,           # gate/up FFN halves
            s * dims.vocab,                      # logits
            s * 4 * dims.hidden,                 # q/k/v/x block
        ]
    else:
        stages = [
            dims.heads * s * geom.max_seq,      # attention scores
            2 * geom.max_seq * dims.kv_dim,     # gathered k+v view
            2 * s * dims.intermediate,          # gate/up FFN halves
            s * dims.vocab,                     # logits
            s * 4 * dims.hidden,                # q/k/v/x block
        ]
    top2 = sum(sorted(stages)[-2:])
    return int(_PREFILL_TEMP_K * top2 * 4)


def _nlayer_slice_temp(dims: ModelDims, batch: int) -> int:
    """Temp floor of the N-layer fused decode program on the CPU ref
    path (r17). The grouped program receives STACKED per-group weights
    and slices one layer per iteration; CPU XLA materializes the sliced
    merged weight feeding each dot instead of fusing the slice, so one
    largest-merged-slice buffer (reused across layers — hence no N
    term) plus the merged activations stays live. Measured fit across
    hidden/intermediate/N sweeps: within 0.7% of compiled temp. The
    Pallas path streams weight tiles through VMEM and never sees this
    buffer; see :func:`plan_fused_layers` for its VMEM pricing."""
    slice_elems = dims.hidden * max(2 * dims.intermediate,
                                    dims.heads * dims.head_dim
                                    + 2 * dims.kv_dim)
    act_elems = batch * (2 * dims.intermediate + 2 * dims.hidden)
    return 4 * (slice_elems + act_elems)


def _kv_dequant_temp(dims: ModelDims, geom: PoolGeometry,
                     batch: int) -> int:
    """int8-KV decode adder (r18): the XLA pool readers gather the
    page payload and materialize ONE f32 dequantized K view of the
    gathered context (the V dequant fuses into the PV dot, and the
    buffer is reused across layers, so there is no per-layer term).
    Fit against CompiledMemoryStats on the tier-1 quantized rows:
    +5.0% on decode_fused int8 at the capture geometry."""
    pages = -(-geom.max_seq // geom.page_size)
    return 4 * dims.kv_heads * batch * pages * geom.page_size \
        * dims.head_dim


def _int4_unpack_temp(dims: ModelDims, group_layers: int) -> int:
    """int4 stacked-weight adder (r18): the CPU/XLA ref path of the
    N-layer program dequantizes the group's packed matrices up front,
    so the group's merged f32 weights land in the temp section — all
    but ``wd``, whose unpack XLA fuses into its consuming dot (the fit
    that lands the banked fully-quantized row at -5.8%). The Pallas
    path unpacks tile-wise in VMEM and never sees these buffers."""
    merged = (dims.hidden * (dims.heads * dims.head_dim
                             + 2 * dims.kv_dim)       # wqkv
              + dims.heads * dims.head_dim * dims.hidden   # wo
              + dims.hidden * 2 * dims.intermediate)       # gate|up
    return 4 * group_layers * merged


def estimate_decode_program(dims: ModelDims, geom: PoolGeometry,
                            batch: int, param_bytes: int,
                            fused_layers: int = 1,
                            int4_weights: bool = False) -> Dict[str, int]:
    """Predicted sections of one decode-step program (fused, generic, or
    the r17 N-layer grouped program — the calibrated model covers all
    three): params + pools + tables in, donated pools + token ids out.

    ``fused_layers`` > 1 prices the ``decode_fused_nlayer`` program.
    Its ARGUMENT section is unchanged: the stacked per-group weight
    copies add exactly the element count of the per-layer block params
    XLA elides as unused, so ``param_bytes`` (all params + buffers)
    still lands on the compiled number. Its temp floor is the stacked
    slice working set (:func:`_nlayer_slice_temp`)."""
    pool = geom.pool_bytes()
    tables = geom.tables_bytes(batch)
    arg = param_bytes + pool + tables + batch * 4         # toks (B,1)
    out = pool + tables + batch * 4                       # argmax ids
    temp = _decode_temp(dims, geom, batch)
    if int(fused_layers) > 1:
        temp = max(temp, _nlayer_slice_temp(dims, batch))
    if geom.kv_quant:
        temp += _kv_dequant_temp(dims, geom, batch)
    if int4_weights:
        temp += _int4_unpack_temp(dims, int(fused_layers))
    return {
        "argument": arg, "output": out,
        "temp": temp,
        "alias": pool, "generated_code": 0,
        "peak": arg + out - pool + temp,
    }


def estimate_prefill_program(dims: ModelDims, geom: PoolGeometry,
                             s: int, param_bytes: int,
                             chunked: bool = False) -> Dict[str, int]:
    """Predicted sections of a b=1 prefill (monolithic length ``s``) or
    chunked-prefill (``s`` = chunk, ``chunked=True`` — the r17
    copy-free block-table path) program."""
    pool = geom.pool_bytes()
    tables = geom.tables_bytes(1)
    arg = param_bytes + pool + tables + s * 4             # ids (1, S)
    out = pool + tables + 4                               # argmax id
    temp = _prefill_temp(dims, geom, s, chunked=chunked)
    return {"argument": arg, "output": out, "temp": temp,
            "alias": pool, "generated_code": 0,
            "peak": arg + out - pool + temp}


# ------------------------------------------------------ what-if planner
_WEIGHT_BYTES = {"float32": 4.0, "f32": 4.0, "bfloat16": 2.0, "bf16": 2.0,
                 "float16": 2.0, "int8": 1.0, "int4": 0.5}


def weight_bytes(param_count: int, dtype: str,
                 scale_group: int = 128) -> int:
    """Model weight bytes for a storage dtype. Quantized dtypes carry
    per-group f32 scales (``scale_group`` weights per scale — the
    streaming-int8 path stores per-channel scales, which this bounds)."""
    per = _WEIGHT_BYTES[str(dtype)]
    total = param_count * per
    if per < 2.0:                       # quantized: add the scales
        total += param_count / scale_group * 4
    return int(total)


class _ShardedDims(ModelDims):
    """Per-shard view of a tensor-parallel serving engine (r19): heads,
    kv-heads and the MLP width divide by ``tp`` while ``head_dim`` stays
    the FULL model's ``hidden // heads`` — the residual stream (and so
    ``hidden``) is replicated, only the head and channel axes shard."""

    __slots__ = ("_head_dim",)

    def __init__(self, dims: ModelDims, tp: int):
        super().__init__(dims.hidden, dims.layers, dims.heads // tp,
                         dims.kv_heads // tp, dims.intermediate // tp,
                         dims.vocab, dims.param_count)
        self._head_dim = dims.head_dim

    @property
    def head_dim(self) -> int:
        return self._head_dim


def estimate_engine_memory(dims: ModelDims, *,
                           page_size: int = 64,
                           page_budget: Optional[int] = None,
                           max_batch: int = 8,
                           max_seq_len: int = 1024,
                           chunk: int = 0,
                           weight_dtype: str = "bfloat16",
                           kv_dtype: str = "bfloat16",
                           host_tier_pages: int = 0,
                           param_count: Optional[int] = None,
                           draft_dims: Optional[ModelDims] = None,
                           spec_gamma: int = 0,
                           draft_param_count: Optional[int] = None,
                           draft_weight_dtype: Optional[str] = None,
                           tp: int = 1
                           ) -> Dict[str, Any]:
    """The what-if planner: predicted steady-state serving HBM for a
    configuration that may be too big to compile locally. Returns the
    transparent breakdown ``tools/memwatch.py plan`` renders; compare
    ``total`` against the chip's HBM. ``page_budget`` = USABLE pages
    (the FLAGS_serving_page_budget contract: +1 null page rides on
    top); None = the worst-case formula. ``host_tier_pages`` (r14)
    prices the host-RAM KV tier alongside: its bytes land under
    ``host_tier`` — host RAM, NOT HBM — so device and host are planned
    jointly but never summed into one number.

    ``draft_dims`` (r16) prices speculative decoding alongside: the
    draft model's weights, its ALWAYS-worst-case KV pool (the engine
    sizes it ``1 + max_batch * pages_per_seq`` regardless of
    ``page_budget`` — draft sync must never fail allocate), and the
    (1, gamma+1) verify chunk's workspace through the TARGET (the
    verify is a chunk program, so it prices exactly like a prefill of
    ``spec_gamma + 1`` positions).

    ``tp`` (r19) prices ONE SHARD of a tensor-parallel engine: the
    stacked block weights split head-/column-/row-wise (embedding and
    lm_head stay replicated, exactly as the sharder leaves them), the
    KV pool partitions over kv-heads — the int8 per-token scale band
    divides with its payload — and the workspaces are re-derived on the
    per-shard dims. Refuses (ValueError) any degree that does not
    divide heads, kv-heads and the MLP width: the engine refuses the
    same configs, and a planner that silently rounded would under-bill.
    Draft-model terms stay replicated — the r16 draft chain runs
    un-sharded on every rank, its pool partitioning is future work."""
    n_params = param_count or dims.param_count
    if n_params is None:
        raise ValueError("need param_count (config.num_params() or "
                         "explicit)")
    tp = int(tp)
    if tp < 1:
        raise ValueError(f"tp must be >= 1, got {tp}")
    if tp > 1 and (dims.heads % tp or dims.kv_heads % tp
                   or dims.intermediate % tp):
        raise ValueError(
            f"tp={tp} must divide heads ({dims.heads}), kv_heads "
            f"({dims.kv_heads}) and intermediate ({dims.intermediate}) "
            f"— the engine refuses this config too")
    if tp > 1 and str(weight_dtype) == "int4":
        raise ValueError(
            "int4 weight tiles cannot be sharded: two-nibble row-pairing "
            "does not commute with the head-shard permutation — the "
            "engine refuses this config too (serve int8 or bf16 under tp)")
    sdims = _ShardedDims(dims, tp) if tp > 1 else dims
    pages_per_seq = -(-max_seq_len // page_size)
    usable = (int(page_budget) if page_budget
              else max_batch * pages_per_seq)
    geom = PoolGeometry(sdims.layers, usable + 1, page_size,
                        sdims.kv_heads,
                        sdims.head_dim, pages_per_seq, np.dtype(
                            "int8" if str(kv_dtype) == "int8"
                            else "float16"),  # 2B stand-in for bf16
                        kv_quant=str(kv_dtype) == "int8")
    if str(kv_dtype) in ("bfloat16", "bf16", "float16"):
        kv_item = 2
    elif str(kv_dtype) == "int8":
        kv_item = 1
    else:
        kv_item = np.dtype(kv_dtype).itemsize
    pool = (sdims.layers * 2 * sdims.kv_heads * (usable + 1) * page_size
            * sdims.head_dim * kv_item)
    if str(kv_dtype) == "int8":
        # per-TOKEN f32 amax scales stored alongside the pool (k and v:
        # one scale per head-token row — write-order-independent, so
        # fault replay stays bit-identical)
        pool += (sdims.layers * 2 * sdims.kv_heads * (usable + 1)
                 * page_size * 4)
    if tp > 1:
        # embedding + lm_head replicate on every shard (the sharder
        # never touches them); every block weight splits exactly /tp.
        # int4/int8 per-group scale tiles ride weight_bytes' per-group
        # scale term, so they divide with their payload.
        replicated = min(int(n_params), 2 * dims.vocab * dims.hidden)
        shard_params = replicated + (int(n_params) - replicated) // tp
        weights = weight_bytes(shard_params, weight_dtype)
    else:
        weights = weight_bytes(n_params, weight_dtype)
    decode_tmp = _decode_temp(sdims, geom, max_batch)
    # chunked prefill is the copy-free block-table path (r17): no
    # gathered full-context K/V view, no full S x max_seq score matrix
    chunk_tmp = (_prefill_temp(sdims, geom, chunk, chunked=True)
                 if chunk else 0)
    tables = geom.tables_bytes(max_batch)
    # ---- speculative decoding (r16): draft weights + worst-case draft
    # pool are resident; the verify chunk and the draft's own programs
    # only add workspace (dispatches never overlap, so max not sum)
    draft_weights = draft_pool = verify_tmp = draft_tmp = 0
    if draft_dims is not None:
        gamma = max(1, int(spec_gamma))
        dn = draft_param_count or draft_dims.param_count
        if dn is None:
            raise ValueError("need draft_param_count "
                             "(config.num_params() or explicit)")
        draft_weights = weight_bytes(
            dn, draft_weight_dtype or weight_dtype)
        dgeom = PoolGeometry(
            draft_dims.layers, 1 + max_batch * pages_per_seq, page_size,
            draft_dims.kv_heads, draft_dims.head_dim, pages_per_seq,
            geom.dtype, kv_quant=geom.kv_quant)
        draft_pool = dgeom.pool_bytes()
        # the verify IS a chunk program — priced on the copy-free path,
        # through the (possibly sharded) TARGET dims
        verify_tmp = _prefill_temp(sdims, geom, gamma + 1, chunked=True)
        draft_tmp = max(_decode_temp(draft_dims, dgeom, 1),
                        _prefill_temp(draft_dims, dgeom, gamma + 1))
    # XLA program text + runtime allocations scale with model size; a
    # visible margin line, not silent slack
    margin = max(64 << 20, int(0.05 * (weights + draft_weights)))
    workspace = max(decode_tmp, chunk_tmp, verify_tmp, draft_tmp)
    total = (weights + draft_weights + pool + draft_pool + workspace
             + tables + margin)
    # host-RAM tier: same per-page geometry as the device pool (spill
    # copies pages verbatim, scales included), priced against HOST
    # memory — derived from the pool term so the two can never drift
    bytes_per_page = pool // (usable + 1)
    host_tier = int(host_tier_pages) * bytes_per_page
    return {
        "dims": {"hidden": dims.hidden, "layers": dims.layers,
                 "heads": dims.heads, "kv_heads": dims.kv_heads,
                 "intermediate": dims.intermediate, "vocab": dims.vocab,
                 "params": int(n_params)},
        "config": {"page_size": page_size, "usable_pages": usable,
                   "max_batch": max_batch, "max_seq_len": max_seq_len,
                   "chunk": chunk, "weight_dtype": str(weight_dtype),
                   "kv_dtype": str(kv_dtype),
                   "host_tier_pages": int(host_tier_pages),
                   "spec_gamma": (max(1, int(spec_gamma))
                                  if draft_dims is not None else 0),
                   "tp": tp},
        "breakdown": {
            "weights": weights, "kv_pool": pool,
            **({"draft_weights": draft_weights,
                "draft_kv_pool": draft_pool,
                "spec_verify_workspace": verify_tmp,
                "draft_workspace": draft_tmp}
               if draft_dims is not None else {}),
            "decode_workspace": decode_tmp,
            "chunk_prefill_workspace": chunk_tmp,
            "block_tables": tables,
            "xla_code_and_runtime_margin": margin,
        },
        "total": int(total),
        "host_tier": {"pages": int(host_tier_pages),
                      "bytes": int(host_tier),
                      "bytes_per_page": int(bytes_per_page)},
    }


def plan_fused_layers(dims: ModelDims, *, fused_layers: int,
                      batch: int = 8, page_size: int = 64,
                      io_dtype_bytes: int = 2,
                      vmem_limit: int = 16 << 20) -> Dict[str, Any]:
    """Price the N-layer fused decode kernel's VMEM working set (r17)
    and say whether ``fused_layers`` fits the per-core VMEM budget.

    Walks the exact tile/scratch shapes ``fused_multi_block_decode_pallas``
    allocates: every block operand is double-buffered by Mosaic (weight
    tiles, the per-layer page blocks — 2 per grouped layer, so the pool
    term is the only one that grows with N), activations/carries are
    persistent f32 VMEM scratch. ``io_dtype_bytes`` is the streamed
    weight/activation storage width (2 = bf16 serving, 4 = f32).
    Returns the transparent breakdown + a ``fits`` verdict against
    ``vmem_limit`` — the ``tools/memwatch.py plan --fused-layers``
    refusal reads it.

    The tile/scratch geometry itself lives in ONE place —
    ``paddle_tpu.analysis.tile_geometry`` — which the kernel imports
    its tiling from and the kernelcheck lint (KRN002) checks the
    kernel source against, so this plan can never silently disagree
    with what the kernel actually allocates (r18)."""
    from ..analysis.tile_geometry import fused_decode_env, price_fused_decode

    n = int(fused_layers)
    if n < 1:
        raise ValueError(f"fused_layers must be >= 1, got {n}")
    env = fused_decode_env(
        hidden=dims.hidden, intermediate=dims.intermediate,
        heads=dims.heads, kv_heads=dims.kv_heads, head_dim=dims.head_dim,
        batch=batch, page_size=page_size)
    priced = price_fused_decode(env, fused_layers=n,
                                io_dtype_bytes=io_dtype_bytes,
                                vmem_limit=vmem_limit)
    return {
        "fused_layers": n, "batch": int(batch), "b_pad": env["b_pad"],
        "page_size": int(page_size), "io_dtype_bytes": int(io_dtype_bytes),
        "breakdown": {
            "weight_stream_buffers": priced["weight_stream_buffers"],
            "activation_io_buffers": priced["activation_io_buffers"],
            "kv_page_buffers": priced["kv_page_buffers"],
            "scratch": priced["scratch"],
        },
        "total": priced["total"],
        "vmem_limit": priced["vmem_limit"],
        "fits": priced["fits"],
        "headroom_bytes": priced["headroom_bytes"],
    }


def fits(plan: Dict[str, Any], hbm_bytes: int) -> Dict[str, Any]:
    """Verdict + headroom for one planner breakdown against a chip."""
    total = plan["total"]
    return {"hbm_bytes": int(hbm_bytes), "total": int(total),
            "fits": total <= hbm_bytes,
            "headroom_bytes": int(hbm_bytes - total)}


# --------------------------------------------- sharded-state accounting
def sharded_param_bytes(shape: Sequence[int], dtype: Any, spec,
                        mesh_shape: Dict[str, int]) -> int:
    """Per-device bytes of one sharded array: per-dim CEIL division (a
    dim not divisible by its mesh axes pads up on device, so flat
    ``total // prod`` would undercount and let a topology pass the fit
    check yet OOM on hardware). The one shard-accounting code path —
    ``PipelineTrainStep.per_device_state_bytes`` and
    ``tools/memory_70b.py`` both call through here."""
    n = 1
    entries = tuple(spec) if spec is not None else ()
    for i, dim in enumerate(shape):
        denom = 1
        if i < len(entries) and entries[i] is not None:
            entry = entries[i]
            for name in ((entry,) if isinstance(entry, str) else entry):
                denom *= int(mesh_shape[name])
        n *= -(-int(dim) // denom)
    return n * np.dtype(dtype).itemsize


# -------------------------------------------------------- regression gate
def compare_program_rows(banked: List[Dict[str, Any]],
                         current: List[Dict[str, Any]],
                         tolerance: float = 0.10) -> List[Dict[str, Any]]:
    """The memory analogue of the zero-retrace gate: flag every program
    whose ``temp`` or ``peak`` grew beyond ``tolerance`` vs the banked
    artifact. Programs only in one table are reported informationally
    (``"missing"``/``"new"``) and do not fail the gate — a config drift
    shows up as growth on the programs both runs share."""
    key = lambda r: (r.get("model", ""), r["kind"], r["bucket"],
                     r.get("extra", ""))
    cur = {key(r): r for r in current}
    findings: List[Dict[str, Any]] = []
    seen = set()
    for row in banked:
        k = key(row)
        seen.add(k)
        now = cur.get(k)
        if now is None:
            findings.append({"model": row.get("model", ""),
                             "kind": row["kind"], "bucket": row["bucket"],
                             "extra": row.get("extra", ""),
                             "verdict": "missing"})
            continue
        for sec in ("temp", "peak"):
            old_v, new_v = int(row.get(sec, 0)), int(now.get(sec, 0))
            # a zero banked value is NOT a free pass: byte sizes are
            # deterministic per backend, so 0 -> anything is real growth
            if new_v > old_v * (1.0 + tolerance) and new_v > old_v:
                findings.append({
                    "model": row.get("model", ""),
                    "kind": row["kind"], "bucket": row["bucket"],
                    "extra": row.get("extra", ""), "section": sec,
                    "banked": old_v, "current": new_v,
                    "growth": (round(new_v / old_v - 1.0, 4)
                               if old_v else None),
                    "verdict": "grew"})
    for k, row in cur.items():
        if k not in seen:
            findings.append({"model": row.get("model", ""),
                             "kind": row["kind"], "bucket": row["bucket"],
                             "extra": row.get("extra", ""),
                             "verdict": "new"})
    return findings
