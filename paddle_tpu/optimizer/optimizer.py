"""Optimizers.

Reference: python/paddle/optimizer/ (Optimizer base, adamw.py, momentum.py, …).
Each optimizer is built around a PURE functional core — ``init_slot(p)`` and
``apply_one(p, g, slots, lr, t)`` — so the exact same math runs:
  * eagerly in ``step()`` (paddle-style: reads ``param.grad``), and
  * inside a jitted train step via ``functional_update`` (a pytree-level
    update the trainer/jit path calls with traced arrays).
The slot layout intentionally matches the reference's accumulator names
(moment1/moment2/beta1_pow/...) so sharded checkpoints map 1:1.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Parameter, Tensor
from ..nn.clip import ClipGradBase
from .lr import LRScheduler


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip: Optional[ClipGradBase] = None, name=None,
                 multi_precision: bool = False):
        self._lr = learning_rate
        self._parameter_list = list(parameters) if parameters is not None else None
        self._weight_decay = 0.0 if weight_decay is None else weight_decay
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._slots: Dict[str, Any] = {}      # param.name -> slot pytree
        self._master: Dict[str, jax.Array] = {}
        self._step_count = 0
        self._param_groups = None
        if parameters and isinstance(parameters[0], dict):
            self._param_groups = parameters
            self._parameter_list = [p for g in parameters for p in g["params"]]

    # ------------------------------------------------------- functional core
    def init_slot(self, p_val: jax.Array) -> Any:
        """Per-parameter optimizer state (override)."""
        return ()

    def apply_one(self, p, g, slots, lr, t, wd):
        """Pure update: returns (new_p, new_slots). Override."""
        raise NotImplementedError

    # -------------------------------------------------------------- lr logic
    def get_lr(self) -> float:
        if isinstance(self._lr, LRScheduler):
            return self._lr()
        return float(self._lr)

    def set_lr(self, value: float):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._lr = float(value)

    def set_lr_scheduler(self, scheduler: LRScheduler):
        self._lr = scheduler

    @property
    def _learning_rate(self):
        return self._lr

    # ------------------------------------------------------------ eager path
    def _params(self) -> List[Parameter]:
        if self._parameter_list is None:
            raise ValueError("optimizer created without a parameter list")
        return [p for p in self._parameter_list if not p.stop_gradient]

    def _wd_excluded_for_param(self, p: Parameter) -> bool:
        """Whether this Parameter is exempt from weight decay. Single source
        of truth for BOTH the eager step() path and (via
        ``resolve_decay_masks``) the jitted functional path — subclasses
        override this, not the two paths separately, so user exclusion
        callbacks always see the eager-contract argument (Parameter or
        p.name), never a pytree key (advisor r2 finding)."""
        return bool(getattr(p, "no_weight_decay", False))

    def _decay_for(self, p: Parameter) -> float:
        if self._wd_excluded_for_param(p):
            return 0.0
        return self._wd_value()

    def step(self):
        params = self._params()
        params_grads = [(p, p.grad) for p in params if p.grad is not None]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        self._step_count += 1
        t = self._step_count
        lr = self.get_lr()
        for p, g in params_grads:
            if g is None:
                continue
            if p.name not in self._slots:
                self._slots[p.name] = self.init_slot(p._value)
            g_val = g._value if isinstance(g, Tensor) else g
            p_val = p._value
            use_master = (self._multi_precision and
                          p_val.dtype in (jnp.float16, jnp.bfloat16))
            if use_master:
                if p.name not in self._master:
                    self._master[p.name] = p_val.astype(jnp.float32)
                p_compute = self._master[p.name]
            else:
                p_compute = p_val
            plr = lr * p.optimize_attr.get("learning_rate", 1.0)
            new_p, new_slots = self.apply_one(
                p_compute, g_val.astype(p_compute.dtype), self._slots[p.name],
                plr, t, self._decay_for(p))
            self._slots[p.name] = new_slots
            if use_master:
                self._master[p.name] = new_p
                p._value = new_p.astype(p_val.dtype)
            else:
                p._value = new_p

    def clear_grad(self, set_to_zero: bool = False):
        for p in self._params():
            p.grad = None

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        from ..core import tensor as _core
        rec = _core._static_recorder
        if rec is not None:
            # static build: record the train marker — Executor.run does
            # backward + step per run (the reference appends backward +
            # optimizer ops to the Program here)
            tag = getattr(loss, "_static_var_id", None)
            if tag is None or tag[0] is not rec.program._family:
                raise ValueError(
                    "minimize(loss): loss is not a variable of the "
                    "program under construction")
            rec.program.train_specs.append((tag[1], self))
            return None, []
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in self._params()]

    # -------------------------------------------------------- jit/GSPMD path
    def init_state_tree(self, params: Dict[str, jax.Array]):
        """State pytree for ``functional_update`` (jitted train step)."""
        return {
            "slots": {k: self.init_slot(v) for k, v in params.items()},
            "t": jnp.zeros((), jnp.int32),
            "master": ({k: v.astype(jnp.float32) for k, v in params.items()}
                       if self._multi_precision else None),
        }

    def functional_update(self, params: Dict[str, jax.Array],
                          grads: Dict[str, jax.Array], state, lr=None):
        """Pure: (params, grads, state) -> (new_params, new_state).
        Safe to call inside jax.jit; lr may be a traced scalar."""
        if lr is None:
            lr = self.get_lr()
        if self._grad_clip is not None and hasattr(self._grad_clip, "clip_tree"):
            grads = self._grad_clip.clip_tree(grads)
        t = state["t"] + 1
        new_params, new_slots = {}, {}
        new_master = {} if state.get("master") is not None else None
        for k, p in params.items():
            g = grads[k]
            slots = state["slots"][k]
            if new_master is not None:
                pc = state["master"][k]
            else:
                pc = p
            np_, ns = self.apply_one(pc, g.astype(pc.dtype), slots, lr, t, self._wd_for_key(k))
            new_slots[k] = ns
            if new_master is not None:
                new_master[k] = np_
                new_params[k] = np_.astype(p.dtype)
            else:
                new_params[k] = np_
        return new_params, {"slots": new_slots, "t": t, "master": new_master}

    def _wd_value(self) -> float:
        wd = self._weight_decay
        if hasattr(wd, "__call__") and not isinstance(wd, (int, float)):
            return float(wd())
        return float(wd)

    def resolve_decay_masks(self, named_params: Dict[str, Parameter]):
        """Pre-resolve the per-parameter decay-exclusion mask keyed by
        pytree key, evaluating user callbacks with their eager-contract
        argument (the Parameter). Called by TrainStep before
        ``init_state_tree``; after this, ``_wd_for_key`` is an exact mirror
        of the eager ``_decay_for``."""
        self._wd_exclusion = {
            k: self._wd_excluded_for_param(p) for k, p in named_params.items()}

    def _wd_for_key(self, key: str) -> float:
        """Per-parameter weight decay in the functional path. Uses the
        mask pre-resolved from Parameters when available; subclasses
        provide a key-string fallback for standalone functional use
        (functional_update without a TrainStep/model)."""
        excl = getattr(self, "_wd_exclusion", None)
        if excl is not None:
            return 0.0 if excl.get(key, False) else self._wd_value()
        return self._wd_fallback_for_key(key)

    def _wd_fallback_for_key(self, key: str) -> float:
        """Key-string exclusion fallback (no Parameter available)."""
        return self._wd_value()

    # ------------------------------------------------------------ state dict
    def state_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for pname, slots in self._slots.items():
            # slot dicts are keyed by the reference accumulator names;
            # serialize as <param>_<slot>_0 (the reference convention)
            for key in sorted(slots) if isinstance(slots, dict) else []:
                out[f"{pname}_{key}_0"] = Tensor(slots[key], stop_gradient=True)
        for pname, m in self._master.items():
            out[f"{pname}_fp32_master_0"] = Tensor(m, stop_gradient=True)
        if isinstance(self._lr, LRScheduler):
            out["LR_Scheduler"] = self._lr.state_dict()
        out["@step"] = self._step_count
        return out

    def set_state_dict(self, state: Dict[str, Any]):
        self._step_count = int(state.get("@step", 0))
        if "LR_Scheduler" in state and isinstance(self._lr, LRScheduler):
            self._lr.set_state_dict(state["LR_Scheduler"])
        if self._parameter_list is None:
            return
        for p in self._params():
            template = self.init_slot(p._value)
            if isinstance(template, dict):
                slots = {}
                for key in template:
                    skey = f"{p.name}_{key}_0"
                    if skey in state:
                        v = state[skey]
                        slots[key] = v._value if isinstance(v, Tensor) else jnp.asarray(v)
                if slots:
                    template.update(slots)
                    self._slots[p.name] = template
            mkey = f"{p.name}_fp32_master_0"
            if mkey in state:
                v = state[mkey]
                self._master[p.name] = v._value if isinstance(v, Tensor) else jnp.asarray(v)


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)

    def apply_one(self, p, g, slots, lr, t, wd):
        if wd:
            g = g + wd * p
        return p - lr * g, slots


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def init_slot(self, p_val):
        return {"velocity": jnp.zeros_like(p_val, dtype=jnp.float32)}

    def apply_one(self, p, g, slots, lr, t, wd):
        if wd:
            g = g + wd * p
        v = self._momentum * slots["velocity"] + g.astype(jnp.float32)
        if self._nesterov:
            upd = g.astype(jnp.float32) + self._momentum * v
        else:
            upd = v
        return (p - lr * upd.astype(p.dtype)), {"velocity": v}



class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, use_multi_tensor=False,
                 name=None, amsgrad=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._decoupled_wd = False   # Adam: L2-reg style decay (coupled)

    def init_slot(self, p_val):
        return {
            "moment1": jnp.zeros_like(p_val, dtype=jnp.float32),
            "moment2": jnp.zeros_like(p_val, dtype=jnp.float32),
        }

    def apply_one(self, p, g, slots, lr, t, wd):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        if wd and not self._decoupled_wd:
            g32 = g32 + wd * p32
        m = self._beta1 * slots["moment1"] + (1 - self._beta1) * g32
        v = self._beta2 * slots["moment2"] + (1 - self._beta2) * (g32 * g32)
        tf = t.astype(jnp.float32) if hasattr(t, "astype") else float(t)
        mhat = m / (1 - self._beta1 ** tf)
        vhat = v / (1 - self._beta2 ** tf)
        upd = mhat / (jnp.sqrt(vhat) + self._eps)
        if wd and self._decoupled_wd:
            upd = upd + wd * p32
        new_p = (p32 - lr * upd).astype(p.dtype)
        return new_p, {"moment1": m, "moment2": v}



class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py);
    supports ``apply_decay_param_fun`` to exempt bias/LN params."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None,
                 apply_decay_param_fun=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, name=None, amsgrad=False):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision,
                         name=name)
        self._decoupled_wd = True
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _wd_excluded_for_param(self, p):
        # reference contract: apply_decay_param_fun receives p.name
        if (self._apply_decay_param_fun is not None
                and not self._apply_decay_param_fun(p.name)):
            return True
        return super()._wd_excluded_for_param(p)

    def _wd_fallback_for_key(self, key):
        # standalone functional use only: the callback sees the pytree key
        if (self._apply_decay_param_fun is not None
                and not self._apply_decay_param_fun(key)):
            return 0.0
        return super()._wd_fallback_for_key(key)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def init_slot(self, p_val):
        return {"moment": jnp.zeros_like(p_val, dtype=jnp.float32),
                "inf_norm": jnp.zeros_like(p_val, dtype=jnp.float32)}

    def apply_one(self, p, g, slots, lr, t, wd):
        g32 = g.astype(jnp.float32)
        if wd:
            g32 = g32 + wd * p.astype(jnp.float32)
        m = self._beta1 * slots["moment"] + (1 - self._beta1) * g32
        u = jnp.maximum(self._beta2 * slots["inf_norm"], jnp.abs(g32))
        tf = t.astype(jnp.float32) if hasattr(t, "astype") else float(t)
        new_p = p - (lr / (1 - self._beta1 ** tf)) * (m / (u + self._eps)).astype(p.dtype)
        return new_p, {"moment": m, "inf_norm": u}



class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._eps, self._momentum, self._centered = rho, epsilon, momentum, centered

    def init_slot(self, p_val):
        s = {"mean_square": jnp.zeros_like(p_val, dtype=jnp.float32),
             "momentum": jnp.zeros_like(p_val, dtype=jnp.float32)}
        if self._centered:
            s["mean_grad"] = jnp.zeros_like(p_val, dtype=jnp.float32)
        return s

    def apply_one(self, p, g, slots, lr, t, wd):
        g32 = g.astype(jnp.float32)
        if wd:
            g32 = g32 + wd * p.astype(jnp.float32)
        ms = self._rho * slots["mean_square"] + (1 - self._rho) * g32 * g32
        if self._centered:
            mg = self._rho * slots["mean_grad"] + (1 - self._rho) * g32
            denom = jnp.sqrt(ms - mg * mg + self._eps)
        else:
            mg = None
            denom = jnp.sqrt(ms + self._eps)
        mom = self._momentum * slots["momentum"] + lr * g32 / denom
        new = {"mean_square": ms, "momentum": mom}
        if mg is not None:
            new["mean_grad"] = mg
        return (p - mom.astype(p.dtype)), new


class Adagrad(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def init_slot(self, p_val):
        return {"moment": jnp.full_like(p_val, self._init_acc, dtype=jnp.float32)}

    def apply_one(self, p, g, slots, lr, t, wd):
        g32 = g.astype(jnp.float32)
        if wd:
            g32 = g32 + wd * p.astype(jnp.float32)
        acc = slots["moment"] + g32 * g32
        new_p = p - (lr * g32 / (jnp.sqrt(acc) + self._eps)).astype(p.dtype)
        return new_p, {"moment": acc}



class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._eps, self._rho = epsilon, rho

    def init_slot(self, p_val):
        return {"avg_squared_grad": jnp.zeros_like(p_val, dtype=jnp.float32),
                "avg_squared_update": jnp.zeros_like(p_val, dtype=jnp.float32)}

    def apply_one(self, p, g, slots, lr, t, wd):
        g32 = g.astype(jnp.float32)
        if wd:
            g32 = g32 + wd * p.astype(jnp.float32)
        asg = self._rho * slots["avg_squared_grad"] + (1 - self._rho) * g32 * g32
        upd = g32 * jnp.sqrt(slots["avg_squared_update"] + self._eps) / jnp.sqrt(asg + self._eps)
        asu = self._rho * slots["avg_squared_update"] + (1 - self._rho) * upd * upd
        return (p - lr * upd.astype(p.dtype)), {
            "avg_squared_grad": asg, "avg_squared_update": asu}



class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, lamb_weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def init_slot(self, p_val):
        return {"moment1": jnp.zeros_like(p_val, dtype=jnp.float32),
                "moment2": jnp.zeros_like(p_val, dtype=jnp.float32)}

    def _wd_excluded_for_param(self, p):
        # reference contract: exclude_from_weight_decay_fn receives the
        # Parameter itself (python/paddle/optimizer/lamb.py)
        if self._exclude_fn is not None and self._exclude_fn(p):
            return True
        return super()._wd_excluded_for_param(p)

    def _wd_fallback_for_key(self, key):
        if self._exclude_fn is not None:
            # the callback takes a Parameter; silently applying full decay
            # (or passing it a str) would corrupt numerics without warning
            raise RuntimeError(
                "Lamb.exclude_from_weight_decay_fn takes a Parameter, which "
                "the standalone functional path does not have — call "
                "resolve_decay_masks(named_params) before functional_update "
                "(TrainStep does this automatically)")
        return super()._wd_fallback_for_key(key)

    def apply_one(self, p, g, slots, lr, t, wd):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        m = self._beta1 * slots["moment1"] + (1 - self._beta1) * g32
        v = self._beta2 * slots["moment2"] + (1 - self._beta2) * g32 * g32
        tf = t.astype(jnp.float32) if hasattr(t, "astype") else float(t)
        mhat = m / (1 - self._beta1 ** tf)
        vhat = v / (1 - self._beta2 ** tf)
        r = mhat / (jnp.sqrt(vhat) + self._eps) + wd * p32
        w_norm = jnp.sqrt(jnp.sum(p32 * p32))
        r_norm = jnp.sqrt(jnp.sum(r * r))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return (p32 - lr * trust * r).astype(p.dtype), {"moment1": m, "moment2": v}



class LarsMomentum(Optimizer):
    """LARS (reference: paddle.incubate.optimizer.LarsMomentumOptimizer /
    lars_momentum op): layer-wise trust ratio scales the LR by
    ||w|| / (||g|| + lars_weight_decay * ||w|| + epsilon)."""

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 lars_coeff=0.001, lars_weight_decay=0.0005,
                 parameters=None, grad_clip=None, epsilon=1e-9,
                 exclude_from_weight_decay=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, lars_weight_decay,
                         grad_clip, name, multi_precision)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._eps = epsilon
        self._exclude = list(exclude_from_weight_decay or [])

    def init_slot(self, p_val):
        return {"velocity": jnp.zeros_like(p_val, dtype=jnp.float32)}

    def _wd_excluded_for_param(self, p) -> bool:
        if any(s in (p.name or "") for s in self._exclude):
            return True
        return super()._wd_excluded_for_param(p)

    def _wd_fallback_for_key(self, key: str) -> float:
        if any(s in key for s in self._exclude):
            return 0.0
        return self._wd_value()

    def apply_one(self, p, g, slots, lr, t, wd):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        w_norm = jnp.sqrt(jnp.sum(p32 * p32))
        g_norm = jnp.sqrt(jnp.sum(g32 * g32))
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self._lars_coeff * w_norm /
            (g_norm + wd * w_norm + self._eps),
            1.0)
        upd = g32 + wd * p32
        v = self._momentum * slots["velocity"] + lr * local_lr * upd
        return (p32 - v).astype(p.dtype), {"velocity": v}


class NAdam(Optimizer):
    """reference: python/paddle/optimizer/nadam.py (Nesterov Adam)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, momentum_decay=0.004, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._psi = momentum_decay

    def init_slot(self, p_val):
        return {"moment1": jnp.zeros_like(p_val, dtype=jnp.float32),
                "moment2": jnp.zeros_like(p_val, dtype=jnp.float32),
                "mu_product": jnp.ones((), jnp.float32)}

    def apply_one(self, p, g, slots, lr, t, wd):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        if wd:
            g32 = g32 + wd * p32
        tf = t.astype(jnp.float32) if hasattr(t, "astype") else float(t)
        mu_t = self._beta1 * (1 - 0.5 * 0.96 ** (tf * self._psi))
        mu_t1 = self._beta1 * (1 - 0.5 * 0.96 ** ((tf + 1) * self._psi))
        mu_prod = slots["mu_product"] * mu_t
        m = self._beta1 * slots["moment1"] + (1 - self._beta1) * g32
        v = self._beta2 * slots["moment2"] + (1 - self._beta2) * g32 * g32
        mhat = (mu_t1 * m / (1 - mu_prod * mu_t1)
                + (1 - mu_t) * g32 / (1 - mu_prod))
        vhat = v / (1 - self._beta2 ** tf)
        new_p = (p32 - lr * mhat / (jnp.sqrt(vhat) + self._eps)).astype(
            p.dtype)
        return new_p, {"moment1": m, "moment2": v, "mu_product": mu_prod}


class RAdam(Optimizer):
    """reference: python/paddle/optimizer/radam.py (rectified Adam)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def init_slot(self, p_val):
        return {"moment1": jnp.zeros_like(p_val, dtype=jnp.float32),
                "moment2": jnp.zeros_like(p_val, dtype=jnp.float32)}

    def apply_one(self, p, g, slots, lr, t, wd):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        if wd:
            g32 = g32 + wd * p32
        tf = t.astype(jnp.float32) if hasattr(t, "astype") else float(t)
        m = self._beta1 * slots["moment1"] + (1 - self._beta1) * g32
        v = self._beta2 * slots["moment2"] + (1 - self._beta2) * g32 * g32
        mhat = m / (1 - self._beta1 ** tf)
        rho_inf = 2.0 / (1 - self._beta2) - 1
        b2t = self._beta2 ** tf
        rho_t = rho_inf - 2 * tf * b2t / (1 - b2t)
        r = jnp.sqrt(jnp.maximum(
            (rho_t - 4) * (rho_t - 2) * rho_inf
            / jnp.maximum((rho_inf - 4) * (rho_inf - 2) * rho_t, 1e-12),
            0.0))
        vhat = jnp.sqrt(v / (1 - b2t)) + self._eps
        upd = jnp.where(rho_t > 5.0, r * mhat / vhat, mhat)
        return (p32 - lr * upd).astype(p.dtype), {"moment1": m, "moment2": v}


class ASGD(Optimizer):
    """reference: python/paddle/optimizer/asgd.py — step with the MEAN of
    the last ``batch_num`` gradients (circular gradient buffer per param;
    costs batch_num x param memory, like the reference's d/ys buffers).
    The live parameter is the iterate (not an averaged copy)."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._n = int(batch_num)

    def init_slot(self, p_val):
        return {"grad_buf": jnp.zeros((self._n,) + tuple(p_val.shape),
                                      jnp.float32),
                "grad_sum": jnp.zeros_like(p_val, dtype=jnp.float32)}

    def apply_one(self, p, g, slots, lr, t, wd):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        if wd:
            g32 = g32 + wd * p32
        tf = t if hasattr(t, "astype") else jnp.asarray(t)
        pos = (tf - 1) % self._n
        old = jax.lax.dynamic_index_in_dim(slots["grad_buf"], pos, 0,
                                           keepdims=False)
        gsum = slots["grad_sum"] - old + g32
        buf = jax.lax.dynamic_update_index_in_dim(
            slots["grad_buf"], g32, pos, 0)
        denom = jnp.minimum(tf.astype(jnp.float32)
                            if hasattr(tf, "astype") else float(tf),
                            float(self._n))
        new_p = (p32 - lr * gsum / denom).astype(p.dtype)
        return new_p, {"grad_buf": buf, "grad_sum": gsum}


class Rprop(Optimizer):
    """reference: python/paddle/optimizer/rprop.py (resilient backprop:
    sign-based per-weight step adaptation)."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         name, multi_precision)
        self._lr_min, self._lr_max = learning_rate_range
        self._eta_minus, self._eta_plus = etas

    def init_slot(self, p_val):
        # scheduler or constant: the initial per-weight step is the
        # CURRENT learning rate (reference rprop.py seeds from the initial
        # lr, not a hardcoded constant — advisor r3)
        return {"prev_grad": jnp.zeros_like(p_val, dtype=jnp.float32),
                "step_size": jnp.full(p_val.shape, float(self.get_lr()),
                                      jnp.float32)}

    def apply_one(self, p, g, slots, lr, t, wd):
        g32 = g.astype(jnp.float32)
        sign = jnp.sign(g32 * slots["prev_grad"])
        step = jnp.clip(
            jnp.where(sign > 0, slots["step_size"] * self._eta_plus,
                      jnp.where(sign < 0,
                                slots["step_size"] * self._eta_minus,
                                slots["step_size"])),
            self._lr_min, self._lr_max)
        g_eff = jnp.where(sign < 0, 0.0, g32)   # no step on sign flip
        new_p = (p.astype(jnp.float32)
                 - jnp.sign(g_eff) * step).astype(p.dtype)
        return new_p, {"prev_grad": g_eff, "step_size": step}


class LBFGS(Optimizer):
    """reference: python/paddle/optimizer/lbfgs.py — limited-memory BFGS
    with closure-based ``step`` (two-loop recursion over a history of
    (s, y) pairs; optional backtracking Armijo line search — the
    reference's strong_wolfe reduces to backtracking on the common path).
    Full-batch/deterministic use, like the reference."""

    def __init__(self, learning_rate=1.0, max_iter=20, tolerance_grad=1e-7,
                 tolerance_change=1e-9, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, name)
        self.max_iter = int(max_iter)
        self.tol_grad = tolerance_grad
        self.tol_change = tolerance_change
        self.history = int(history_size)
        self.line_search_fn = line_search_fn
        self._s, self._y = [], []
        self._prev_flat = None
        self._prev_grad = None

    # -- flat helpers ------------------------------------------------------
    def _flat(self, vals):
        return jnp.concatenate([v.reshape(-1).astype(jnp.float32)
                                for v in vals])

    def _unflat(self, flat):
        out = []
        off = 0
        for p in self._params():
            n = int(np.prod(p._value.shape))
            out.append(flat[off:off + n].reshape(p._value.shape)
                       .astype(p._value.dtype))
            off += n
        return out

    def _grad_flat(self):
        return self._flat([p.grad._value if isinstance(p.grad, Tensor)
                           else p.grad for p in self._params()])

    def _direction(self, g):
        q = g
        alphas = []
        for s, y in zip(reversed(self._s), reversed(self._y)):
            rho = 1.0 / jnp.vdot(y, s)
            a = rho * jnp.vdot(s, q)
            q = q - a * y
            alphas.append((a, rho, s, y))
        gamma = 1.0
        if self._s:
            s, y = self._s[-1], self._y[-1]
            gamma = jnp.vdot(s, y) / jnp.vdot(y, y)
        r = gamma * q
        for a, rho, s, y in reversed(alphas):
            b = rho * jnp.vdot(y, r)
            r = r + (a - b) * s
        return -r

    def step(self, closure=None):
        if closure is None:
            raise ValueError("LBFGS.step needs a closure computing the "
                             "loss with backward()")
        loss = closure()
        params = self._params()
        flat = self._flat([p._value for p in params])
        g = self._grad_flat()

        if self._prev_flat is not None:
            s = flat - self._prev_flat
            y = g - self._prev_grad
            if float(jnp.vdot(s, y)) > 1e-10:
                self._s.append(s)
                self._y.append(y)
                if len(self._s) > self.history:
                    self._s.pop(0)
                    self._y.pop(0)

        d = self._direction(g)
        lr = self.get_lr()
        if self.line_search_fn in ("strong_wolfe", "backtracking"):
            f0 = float(loss)
            gd = float(jnp.vdot(g, d))
            t = lr
            for _ in range(10):
                for p, nv in zip(params, self._unflat(flat + t * d)):
                    p._value = nv
                self.clear_grad()
                f1 = float(closure())
                if f1 <= f0 + 1e-4 * t * gd:
                    break
                t *= 0.5
        else:
            for p, nv in zip(params, self._unflat(flat + lr * d)):
                p._value = nv
        # Pair with the *evaluation* point: next step forms
        # s = x_{k+1} - x_k and y = g_{k+1} - g_k. Saving the post-update
        # params here would make s identically zero.
        self._prev_flat = flat
        self._prev_grad = g
        self._step_count += 1
        return loss
