"""GPT exemplar (the smoke-config model: GPT-3 345M).

Built entirely from paddle_tpu.nn layers so that the same model definition
runs eagerly, under jit, and — once wrapped by fleet — under hybrid
parallelism. TP-aware variants swap Linear for Column/RowParallelLinear via
``mesh_axes`` hints consumed by the fleet wrappers (meta_parallel).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import numpy as np

from .. import ops
from ..core.tensor import Tensor
from ..generation import GenerationMixin
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer, LayerList
from ..nn.layers.common import Dropout, Embedding, LayerNorm, Linear
from ..nn.param_attr import ParamAttr


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 1024
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    intermediate_size: Optional[int] = None
    max_position_embeddings: int = 1024
    hidden_dropout_prob: float = 0.0
    attention_probs_dropout_prob: float = 0.0
    initializer_range: float = 0.02
    layer_norm_epsilon: float = 1e-5
    tie_word_embeddings: bool = True

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size

    @staticmethod
    def gpt3_345m() -> "GPTConfig":
        return GPTConfig(hidden_size=1024, num_hidden_layers=24,
                         num_attention_heads=16)

    @staticmethod
    def tiny() -> "GPTConfig":
        return GPTConfig(vocab_size=512, hidden_size=64, num_hidden_layers=2,
                         num_attention_heads=4, max_position_embeddings=128)

    def num_params(self) -> int:
        h, l, v = self.hidden_size, self.num_hidden_layers, self.vocab_size
        per_layer = 4 * h * h + 2 * h * self.intermediate_size  # attn + mlp
        per_layer += 4 * h + 2 * self.intermediate_size         # biases
        per_layer += 4 * h                                       # 2x LN
        emb = v * h + self.max_position_embeddings * h
        return l * per_layer + emb + 2 * h


class GPTSelfAttention(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        h = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.head_dim = h // self.num_heads
        init = I.Normal(0.0, config.initializer_range)
        self.qkv_proj = Linear(h, 3 * h, weight_attr=ParamAttr(initializer=init))
        self.out_proj = Linear(
            h, h, weight_attr=ParamAttr(
                initializer=I.Normal(0.0, config.initializer_range /
                                     math.sqrt(2 * config.num_hidden_layers))))
        self.attn_drop_p = config.attention_probs_dropout_prob

    def forward(self, x, attn_mask=None, cache=None):
        from ..kernels.paged_attention import is_paged_state

        b, s, h = x.shape
        qkv = self.qkv_proj(x).reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = ops.unbind(qkv, axis=2)
        if cache is not None and is_paged_state(cache[0]):
            state, _offset = cache
            out, state = F.paged_scaled_dot_product_attention(q, k, v, state)
            return self.out_proj(out.reshape([b, s, h])), state
        if cache is not None:
            k_cache, v_cache, offset = cache
            out, k_cache, v_cache = F.cached_scaled_dot_product_attention(
                q, k, v, k_cache, v_cache, offset)
            return self.out_proj(out.reshape([b, s, h])), (k_cache, v_cache)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, is_causal=attn_mask is None,
            dropout_p=self.attn_drop_p if self.training else 0.0,
            training=self.training)
        return self.out_proj(out.reshape([b, s, h]))


class GPTMLP(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        init = I.Normal(0.0, config.initializer_range)
        self.fc_in = Linear(config.hidden_size, config.intermediate_size,
                            weight_attr=ParamAttr(initializer=init))
        self.fc_out = Linear(
            config.intermediate_size, config.hidden_size,
            weight_attr=ParamAttr(initializer=I.Normal(
                0.0, config.initializer_range / math.sqrt(2 * config.num_hidden_layers))))

    def forward(self, x):
        return self.fc_out(F.gelu(self.fc_in(x), approximate=True))


class GPTBlock(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln_1 = LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.attn = GPTSelfAttention(config)
        self.ln_2 = LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.mlp = GPTMLP(config)
        self.drop = Dropout(config.hidden_dropout_prob)

    def forward(self, x, attn_mask=None, cache=None):
        if cache is not None:
            attn, new_cache = self.attn(self.ln_1(x), attn_mask, cache)
            x = x + self.drop(attn)
            x = x + self.drop(self.mlp(self.ln_2(x)))
            return x, new_cache
        x = x + self.drop(self.attn(self.ln_1(x), attn_mask))
        x = x + self.drop(self.mlp(self.ln_2(x)))
        return x


class GPTModel(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        init = I.Normal(0.0, config.initializer_range)
        self.wte = Embedding(config.vocab_size, config.hidden_size,
                             weight_attr=ParamAttr(initializer=init))
        self.wpe = Embedding(config.max_position_embeddings, config.hidden_size,
                             weight_attr=ParamAttr(initializer=init))
        self.drop = Dropout(config.hidden_dropout_prob)
        self.h = LayerList([GPTBlock(config) for _ in range(config.num_hidden_layers)])
        self.ln_f = LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)

    def forward(self, input_ids, attn_mask=None, position_ids=None,
                caches=None, offset=None):
        b, s = input_ids.shape
        if position_ids is None:
            position_ids = self._position_ids(s, offset, caches)
        x = self.wte(input_ids) + self.wpe(position_ids)
        x = self.drop(x)
        if caches is not None:
            from ..kernels.paged_attention import is_paged_state
            new_caches = []
            for block, entry in zip(self.h, caches):
                if is_paged_state(entry):
                    x, nc = block(x, attn_mask, cache=(entry, offset))
                else:
                    kc, vc = entry
                    x, nc = block(x, attn_mask, cache=(kc, vc, offset))
                new_caches.append(nc)
            return self.ln_f(x), new_caches
        for block in self.h:
            x = block(x, attn_mask)
        return self.ln_f(x)

    def _position_ids(self, s, offset, caches):
        from ..kernels.paged_attention import (is_paged_state,
                                               paged_position_ids)
        if caches and is_paged_state(caches[0]):
            return paged_position_ids(s, offset, caches[0], "int64")
        base = ops.arange(s, dtype="int64").unsqueeze(0)
        return base if offset is None else base + offset


class GPTEmbeddingPipe(Layer):
    """First pipeline entry: token + position embedding (+ dropout).
    Shared (tied) with the head via SharedLayerDesc key "embed"."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        init = I.Normal(0.0, config.initializer_range)
        self.wte = Embedding(config.vocab_size, config.hidden_size,
                             weight_attr=ParamAttr(initializer=init))
        self.wpe = Embedding(config.max_position_embeddings, config.hidden_size,
                             weight_attr=ParamAttr(initializer=init))
        self.drop = Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids):
        b, s = input_ids.shape
        position_ids = ops.arange(s, dtype="int64").unsqueeze(0)
        return self.drop(self.wte(input_ids) + self.wpe(position_ids))


def _embedding_as_head(layer: GPTEmbeddingPipe, hidden):
    """forward_func for the tied head occurrence: logits via wte^T."""
    return ops.matmul(hidden, layer.wte.weight, transpose_y=True)


class GPTPretrainingCriterion(Layer):
    """loss_fn for the pipe model: mean CE over all tokens."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.vocab_size = config.vocab_size

    def forward(self, logits, labels):
        return F.cross_entropy(logits.reshape([-1, self.vocab_size]),
                               labels.reshape([-1]), reduction="mean")


def GPTForCausalLMPipe(config: GPTConfig, num_stages: Optional[int] = None,
                       topology=None, seg_method: str = "layer:GPTBlock",
                       recompute_interval: int = 0):
    """The pipeline-parallel GPT exemplar (reference: PaddleNLP's
    GPTForCausalLMPipe(PipelineLayer); the PipelineLayer mechanics are
    SURVEY.md §2.2 "meta_parallel: PP"). Returns a PipelineLayer whose
    uniform GPTBlock region is stacked over the pp mesh axis by
    PipelineTrainStep."""
    from ..distributed.fleet.meta_parallel.pp_layers import (
        LayerDesc, PipelineLayer, SharedLayerDesc)

    descs = [
        SharedLayerDesc("embed", GPTEmbeddingPipe, None, "wte.weight", config),
    ]
    descs += [LayerDesc(GPTBlock, config)
              for _ in range(config.num_hidden_layers)]
    descs.append(LayerDesc(LayerNorm, config.hidden_size,
                           epsilon=config.layer_norm_epsilon))
    if config.tie_word_embeddings:
        descs.append(SharedLayerDesc(
            "embed", GPTEmbeddingPipe, _embedding_as_head, "wte.weight",
            config))
    else:
        descs.append(LayerDesc(Linear, config.hidden_size, config.vocab_size,
                               bias_attr=False))
    return PipelineLayer(
        descs, num_stages=num_stages, topology=topology,
        loss_fn=GPTPretrainingCriterion(config), seg_method=seg_method,
        recompute_interval=recompute_interval)


class GPTForCausalLM(GenerationMixin, Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None  # logits via wte.T
        else:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  bias_attr=False)

    def logits(self, hidden):
        if self.lm_head is None:
            return ops.matmul(hidden, self.gpt.wte.weight, transpose_y=True)
        return self.lm_head(hidden)

    def forward(self, input_ids, labels=None, attn_mask=None, position_ids=None):
        hidden = self.gpt(input_ids, attn_mask, position_ids)
        if labels is None:
            return self.logits(hidden)
        # chunked fused LM loss: never materializes (tokens, vocab) f32
        from ..incubate.nn import functional as IF
        if self.lm_head is None:
            return IF.fused_linear_cross_entropy(
                hidden, self.gpt.wte.weight, labels, transpose_y=True)
        return IF.fused_linear_cross_entropy(
            hidden, self.lm_head.weight, labels, transpose_y=False)

    # ---- decode path (GenerationMixin hooks) -----------------------------
    def cache_spec(self):
        c = self.config
        return [(c.num_attention_heads,
                 c.hidden_size // c.num_attention_heads)
                for _ in range(c.num_hidden_layers)]

    def forward_with_cache(self, input_ids, caches, offset):
        hidden, new_caches = self.gpt(input_ids, caches=caches, offset=offset)
        return self.logits(hidden), new_caches
