"""BERT / ERNIE encoder family (reference model zoo:
paddlenlp/transformers/{bert,ernie}/modeling.py — the ecosystem's
flagship encoder models; architecture per Devlin et al. / ERNIE 1.0,
which shares the BERT encoder and differs in pretraining data/masking
and ``type_vocab_size``).

Built from paddle_tpu.nn layers exactly like the GPT exemplar: the same
definition runs eagerly, under jit, and under the fleet wrappers. All
attention is bidirectional over a padding mask; pretraining losses use
ignore_index=-100 semantics so masked-LM labels need no separate weight
tensor.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

from .. import ops
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer, LayerList
from ..nn.layers.common import Dropout, Embedding, LayerNorm, Linear
from ..nn.param_attr import ParamAttr

__all__ = [
    "BertConfig", "BertModel", "BertForPretraining", "BertForMaskedLM",
    "BertForSequenceClassification", "BertPretrainingCriterion",
    "ErnieModel",
]


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: Optional[int] = None
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    initializer_range: float = 0.02
    layer_norm_epsilon: float = 1e-12
    pad_token_id: int = 0

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size

    @staticmethod
    def bert_base() -> "BertConfig":
        return BertConfig()

    @staticmethod
    def ernie_base() -> "BertConfig":
        # ERNIE 1.0 zh: same encoder, 18000-word vocab
        return BertConfig(vocab_size=18000)

    @staticmethod
    def tiny() -> "BertConfig":
        return BertConfig(vocab_size=512, hidden_size=64,
                          num_hidden_layers=2, num_attention_heads=4,
                          max_position_embeddings=128,
                          hidden_dropout_prob=0.0,
                          attention_probs_dropout_prob=0.0)


class BertEmbeddings(Layer):
    """word + position + token_type embeddings -> LN -> dropout."""

    def __init__(self, config: BertConfig):
        super().__init__()
        init = I.Normal(0.0, config.initializer_range)
        attr = ParamAttr(initializer=init)
        self.word_embeddings = Embedding(config.vocab_size,
                                         config.hidden_size,
                                         weight_attr=attr)
        self.position_embeddings = Embedding(
            config.max_position_embeddings, config.hidden_size,
            weight_attr=attr)
        self.token_type_embeddings = Embedding(
            config.type_vocab_size, config.hidden_size, weight_attr=attr)
        self.layer_norm = LayerNorm(config.hidden_size,
                                    epsilon=config.layer_norm_epsilon)
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        b, s = input_ids.shape
        if position_ids is None:
            position_ids = ops.arange(s, dtype="int64").unsqueeze(0)
        if token_type_ids is None:
            token_type_ids = ops.zeros([b, s], dtype="int64")
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(x))


class BertSelfAttention(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        h = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.head_dim = h // self.num_heads
        attr = ParamAttr(initializer=I.Normal(0.0,
                                              config.initializer_range))
        self.qkv_proj = Linear(h, 3 * h, weight_attr=attr)
        self.out_proj = Linear(h, h, weight_attr=attr)
        self.attn_drop_p = config.attention_probs_dropout_prob

    def forward(self, x, attn_mask=None):
        b, s, h = x.shape
        qkv = self.qkv_proj(x).reshape(
            [b, s, 3, self.num_heads, self.head_dim])
        q, k, v = ops.unbind(qkv, axis=2)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, is_causal=False,
            dropout_p=self.attn_drop_p if self.training else 0.0,
            training=self.training)
        return self.out_proj(out.reshape([b, s, h]))


class BertLayer(Layer):
    """Post-LN transformer block (the original BERT residual order:
    LN(x + sublayer(x)), vs GPT's pre-LN)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        attr = ParamAttr(initializer=I.Normal(0.0,
                                              config.initializer_range))
        self.attention = BertSelfAttention(config)
        self.ln_1 = LayerNorm(config.hidden_size,
                              epsilon=config.layer_norm_epsilon)
        self.fc_in = Linear(config.hidden_size, config.intermediate_size,
                            weight_attr=attr)
        self.fc_out = Linear(config.intermediate_size, config.hidden_size,
                             weight_attr=attr)
        self.ln_2 = LayerNorm(config.hidden_size,
                              epsilon=config.layer_norm_epsilon)
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, x, attn_mask=None):
        x = self.ln_1(x + self.dropout(self.attention(x, attn_mask)))
        mlp = self.fc_out(F.gelu(self.fc_in(x), approximate=True))
        return self.ln_2(x + self.dropout(mlp))


class BertPooler(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.dense = Linear(
            config.hidden_size, config.hidden_size,
            weight_attr=ParamAttr(initializer=I.Normal(
                0.0, config.initializer_range)))

    def forward(self, hidden):
        return ops.tanh(self.dense(hidden[:, 0]))


class BertModel(Layer):
    """Returns ``(sequence_output, pooled_output)`` like the reference
    BertModel. ``attention_mask``: (B, S) with 1 = real token, 0 = pad
    (the reference convention); converted to an additive (B, 1, 1, S)
    key mask broadcast over heads and query positions."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.encoder = LayerList(
            [BertLayer(config) for _ in range(config.num_hidden_layers)])
        self.pooler = BertPooler(config)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                position_ids=None):
        if attention_mask is None:
            attention_mask = (input_ids !=
                              self.config.pad_token_id).astype("int64")
        add_mask = ((1.0 - attention_mask.astype("float32"))
                    * -1e30).unsqueeze(1).unsqueeze(1)    # (B, 1, 1, S)
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        for layer in self.encoder:
            x = layer(x, add_mask)
        return x, self.pooler(x)


class BertLMPredictionHead(Layer):
    """MLM transform + decoder tied to the word embedding matrix."""

    def __init__(self, config: BertConfig, embedding_weights):
        super().__init__()
        self.transform = Linear(
            config.hidden_size, config.hidden_size,
            weight_attr=ParamAttr(initializer=I.Normal(
                0.0, config.initializer_range)))
        self.layer_norm = LayerNorm(config.hidden_size,
                                    epsilon=config.layer_norm_epsilon)
        self.decoder_weight = embedding_weights          # tied, (V, H)
        self.decoder_bias = self.create_parameter(
            [config.vocab_size], is_bias=True)

    def forward(self, hidden):
        h = self.layer_norm(F.gelu(self.transform(hidden),
                                   approximate=True))
        return ops.matmul(h, self.decoder_weight,
                          transpose_y=True) + self.decoder_bias


class BertForPretraining(Layer):
    """MLM + NSP heads (reference BertForPretraining)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.cls = BertLMPredictionHead(
            config, self.bert.embeddings.word_embeddings.weight)
        self.nsp = Linear(config.hidden_size, 2,
                          weight_attr=ParamAttr(initializer=I.Normal(
                              0.0, config.initializer_range)))

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        return self.cls(seq), self.nsp(pooled)


class BertPretrainingCriterion(Layer):
    """MLM CE over labeled positions (label -100 = unlabeled) + NSP CE."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.vocab_size = config.vocab_size

    def forward(self, prediction_logits, nsp_logits, masked_lm_labels,
                next_sentence_labels=None):
        mlm = F.cross_entropy(
            prediction_logits.reshape([-1, self.vocab_size]),
            masked_lm_labels.reshape([-1]), ignore_index=-100,
            reduction="mean")
        if next_sentence_labels is None:
            return mlm
        nsp = F.cross_entropy(nsp_logits,
                              next_sentence_labels.reshape([-1]),
                              reduction="mean")
        return mlm + nsp


class BertForMaskedLM(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.cls = BertLMPredictionHead(
            config, self.bert.embeddings.word_embeddings.weight)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                labels=None):
        seq, _ = self.bert(input_ids, token_type_ids, attention_mask)
        logits = self.cls(seq)
        if labels is None:
            return logits
        loss = F.cross_entropy(
            logits.reshape([-1, self.bert.config.vocab_size]),
            labels.reshape([-1]), ignore_index=-100, reduction="mean")
        return logits, loss


class BertForSequenceClassification(Layer):
    def __init__(self, config: BertConfig, num_classes: int = 2):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = Dropout(config.hidden_dropout_prob)
        self.classifier = Linear(
            config.hidden_size, num_classes,
            weight_attr=ParamAttr(initializer=I.Normal(
                0.0, config.initializer_range)))

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        return self.classifier(self.dropout(pooled))


# ERNIE 1.0 shares the BERT encoder exactly (the pretraining objectives
# differ, not the module graph) — the reference exposes it as its own
# class; alias it so ecosystem code reads naturally.
ErnieModel = BertModel
