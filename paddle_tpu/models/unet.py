"""Stable-Diffusion-style conditional UNet exemplar (BASELINE configs[4]).

Reference parity target: PaddleMIX's ppdiffusers ``UNet2DConditionModel``
(itself mirroring diffusers), which the reference framework trains through
its PHI conv/groupnorm kernels (SURVEY.md §1 note). Here the model is built
entirely from paddle_tpu.nn layers: Conv2D lowers to
``lax.conv_general_dilated`` (XLA tiles it onto the MXU), GroupNorm/SiLU
fuse into the surrounding convs under jit, and attention uses the shared
``scaled_dot_product_attention`` (Pallas flash kernel at long sequence).

Architecture (SD 1.x shape): conv_in -> down blocks (ResNet x N
[+ cross/self attention] + stride-2 downsample) -> mid (ResNet, attention,
ResNet) -> up blocks mirroring down with skip concats + nearest-neighbor
upsample -> GroupNorm/SiLU/conv_out. Timesteps enter via sinusoidal
embedding + MLP, added inside every ResNet block.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import numpy as np

from .. import ops
from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn.layer import Layer, LayerList
from ..nn.layers.common import Conv2D, GroupNorm, LayerNorm, Linear

__all__ = ["UNetConfig", "UNet2DConditionModel", "UNetDenoiseLoss"]


@dataclasses.dataclass
class UNetConfig:
    sample_size: int = 64              # latent H=W
    in_channels: int = 4
    out_channels: int = 4
    block_out_channels: Tuple[int, ...] = (320, 640, 1280, 1280)
    layers_per_block: int = 2
    # which down blocks carry cross-attention (mirrored for up blocks);
    # SD 1.x: all but the last (lowest-resolution) down block
    cross_attention_blocks: Optional[Sequence[bool]] = None
    cross_attention_dim: int = 768
    num_attention_heads: int = 8       # SD 1.x: 8 heads, head_dim = C // 8
    norm_num_groups: int = 32
    freq_shift: float = 0.0

    def __post_init__(self):
        if self.cross_attention_blocks is None:
            n = len(self.block_out_channels)
            self.cross_attention_blocks = tuple(
                [True] * (n - 1) + [False])

    @staticmethod
    def sd15() -> "UNetConfig":
        return UNetConfig()

    @staticmethod
    def tiny() -> "UNetConfig":
        return UNetConfig(sample_size=16, block_out_channels=(32, 64),
                          layers_per_block=1, cross_attention_dim=32,
                          num_attention_heads=4, norm_num_groups=8)


def _timestep_embedding(t, dim: int, freq_shift: float = 0.0,
                        max_period: float = 10000.0):
    """Sinusoidal embedding (reference: ppdiffusers get_timestep_embedding)."""
    half = dim // 2
    freqs = ops.exp(
        ops.arange(half, dtype="float32") *
        (-math.log(max_period) / (half - freq_shift)))
    args = t.astype("float32").unsqueeze(-1) * freqs.unsqueeze(0)
    return ops.concat([ops.cos(args), ops.sin(args)], axis=-1)


class ResnetBlock2D(Layer):
    def __init__(self, in_c: int, out_c: int, temb_c: int, groups: int):
        super().__init__()
        self.norm1 = GroupNorm(min(groups, in_c), in_c)
        self.conv1 = Conv2D(in_c, out_c, 3, padding=1)
        self.time_emb_proj = Linear(temb_c, out_c)
        self.norm2 = GroupNorm(min(groups, out_c), out_c)
        self.conv2 = Conv2D(out_c, out_c, 3, padding=1)
        self.shortcut = (Conv2D(in_c, out_c, 1) if in_c != out_c else None)

    def forward(self, x, temb):
        h = self.conv1(F.silu(self.norm1(x)))
        h = h + self.time_emb_proj(F.silu(temb)).unsqueeze(-1).unsqueeze(-1)
        h = self.conv2(F.silu(self.norm2(h)))
        skip = x if self.shortcut is None else self.shortcut(x)
        return skip + h


class Attention(Layer):
    """Multi-head attention over flattened spatial tokens; optional
    cross-attention context."""

    def __init__(self, query_dim: int, context_dim: Optional[int],
                 num_heads: int):
        super().__init__()
        self.heads = num_heads
        self.head_dim = query_dim // self.heads
        kv_dim = context_dim if context_dim is not None else query_dim
        self.to_q = Linear(query_dim, query_dim, bias_attr=False)
        self.to_k = Linear(kv_dim, query_dim, bias_attr=False)
        self.to_v = Linear(kv_dim, query_dim, bias_attr=False)
        self.to_out = Linear(query_dim, query_dim)

    def forward(self, x, context=None):
        ctx = x if context is None else context
        b, s, _ = x.shape
        t = ctx.shape[1]
        q = self.to_q(x).reshape([b, s, self.heads, self.head_dim])
        k = self.to_k(ctx).reshape([b, t, self.heads, self.head_dim])
        v = self.to_v(ctx).reshape([b, t, self.heads, self.head_dim])
        out = F.scaled_dot_product_attention(q, k, v)
        return self.to_out(out.reshape([b, s, self.heads * self.head_dim]))


class FeedForward(Layer):
    """GEGLU feed-forward (reference: ppdiffusers FeedForward/GEGLU)."""

    def __init__(self, dim: int, mult: int = 4):
        super().__init__()
        self.proj_in = Linear(dim, dim * mult * 2)
        self.proj_out = Linear(dim * mult, dim)

    def forward(self, x):
        h, gate = ops.chunk(self.proj_in(x), 2, axis=-1)
        return self.proj_out(h * F.gelu(gate))


class BasicTransformerBlock(Layer):
    def __init__(self, dim: int, context_dim: int, num_heads: int):
        super().__init__()
        self.norm1 = LayerNorm(dim)
        self.attn1 = Attention(dim, None, num_heads)
        self.norm2 = LayerNorm(dim)
        self.attn2 = Attention(dim, context_dim, num_heads)
        self.norm3 = LayerNorm(dim)
        self.ff = FeedForward(dim)

    def forward(self, x, context):
        x = x + self.attn1(self.norm1(x))
        x = x + self.attn2(self.norm2(x), context)
        x = x + self.ff(self.norm3(x))
        return x


class Transformer2D(Layer):
    """GroupNorm + 1x1 proj in, one BasicTransformerBlock over flattened
    spatial tokens, 1x1 proj out with residual."""

    def __init__(self, channels: int, context_dim: int, num_heads: int,
                 groups: int):
        super().__init__()
        self.norm = GroupNorm(min(groups, channels), channels)
        self.proj_in = Conv2D(channels, channels, 1)
        self.block = BasicTransformerBlock(channels, context_dim, num_heads)
        self.proj_out = Conv2D(channels, channels, 1)

    def forward(self, x, context):
        b, c, h, w = x.shape
        res = x
        y = self.proj_in(self.norm(x))
        y = y.reshape([b, c, h * w]).transpose([0, 2, 1])
        y = self.block(y, context)
        y = y.transpose([0, 2, 1]).reshape([b, c, h, w])
        return res + self.proj_out(y)


class Downsample2D(Layer):
    def __init__(self, channels: int):
        super().__init__()
        self.conv = Conv2D(channels, channels, 3, stride=2, padding=1)

    def forward(self, x):
        return self.conv(x)


class Upsample2D(Layer):
    def __init__(self, channels: int):
        super().__init__()
        self.conv = Conv2D(channels, channels, 3, padding=1)

    def forward(self, x):
        return self.conv(F.interpolate(x, scale_factor=2, mode="nearest"))


class DownBlock(Layer):
    def __init__(self, in_c, out_c, temb_c, cfg: UNetConfig, attn: bool,
                 downsample: bool):
        super().__init__()
        self.resnets = LayerList([
            ResnetBlock2D(in_c if i == 0 else out_c, out_c, temb_c,
                          cfg.norm_num_groups)
            for i in range(cfg.layers_per_block)])
        self.attentions = (LayerList([
            Transformer2D(out_c, cfg.cross_attention_dim,
                          cfg.num_attention_heads, cfg.norm_num_groups)
            for _ in range(cfg.layers_per_block)]) if attn else None)
        self.downsample = Downsample2D(out_c) if downsample else None

    def forward(self, x, temb, context):
        skips = []
        for i, res in enumerate(self.resnets):
            x = res(x, temb)
            if self.attentions is not None:
                x = self.attentions[i](x, context)
            skips.append(x)
        if self.downsample is not None:
            x = self.downsample(x)
            skips.append(x)
        return x, skips


class UpBlock(Layer):
    def __init__(self, in_c, skip_c_list, out_c, temb_c, cfg: UNetConfig,
                 attn: bool, upsample: bool):
        super().__init__()
        self.resnets = LayerList([
            ResnetBlock2D((in_c if i == 0 else out_c) + skip_c_list[i],
                          out_c, temb_c, cfg.norm_num_groups)
            for i in range(len(skip_c_list))])
        self.attentions = (LayerList([
            Transformer2D(out_c, cfg.cross_attention_dim,
                          cfg.num_attention_heads, cfg.norm_num_groups)
            for _ in range(len(skip_c_list))]) if attn else None)
        self.upsample = Upsample2D(out_c) if upsample else None

    def forward(self, x, skips, temb, context):
        for i, res in enumerate(self.resnets):
            x = ops.concat([x, skips.pop()], axis=1)
            x = res(x, temb)
            if self.attentions is not None:
                x = self.attentions[i](x, context)
        if self.upsample is not None:
            x = self.upsample(x)
        return x


class MidBlock(Layer):
    def __init__(self, channels, temb_c, cfg: UNetConfig):
        super().__init__()
        self.resnet1 = ResnetBlock2D(channels, channels, temb_c,
                                     cfg.norm_num_groups)
        self.attention = Transformer2D(channels, cfg.cross_attention_dim,
                                       cfg.num_attention_heads,
                                       cfg.norm_num_groups)
        self.resnet2 = ResnetBlock2D(channels, channels, temb_c,
                                     cfg.norm_num_groups)

    def forward(self, x, temb, context):
        x = self.resnet1(x, temb)
        x = self.attention(x, context)
        return self.resnet2(x, temb)


class UNet2DConditionModel(Layer):
    """The conditional denoiser: ``forward(sample, timestep,
    encoder_hidden_states) -> noise prediction`` (NCHW latents)."""

    def __init__(self, config: UNetConfig):
        super().__init__()
        self.config = config
        ch = config.block_out_channels
        temb_c = ch[0] * 4
        self.time_proj_dim = ch[0]
        self.time_embedding = LayerList(
            [Linear(ch[0], temb_c), Linear(temb_c, temb_c)])
        self.conv_in = Conv2D(config.in_channels, ch[0], 3, padding=1)

        self.down_blocks = LayerList()
        in_c = ch[0]
        for i, out_c in enumerate(ch):
            last = i == len(ch) - 1
            self.down_blocks.append(DownBlock(
                in_c, out_c, temb_c, config,
                attn=config.cross_attention_blocks[i], downsample=not last))
            in_c = out_c

        self.mid_block = MidBlock(ch[-1], temb_c, config)

        # mirror the down path: skip channels in reverse order
        skip_channels = [ch[0]]  # conv_in output
        for i, out_c in enumerate(ch):
            skip_channels += [out_c] * config.layers_per_block
            if i != len(ch) - 1:
                skip_channels.append(out_c)
        self.up_blocks = LayerList()
        in_c = ch[-1]
        for i in reversed(range(len(ch))):
            out_c = ch[i]
            n_res = config.layers_per_block + 1
            skips = [skip_channels.pop() for _ in range(n_res)]
            self.up_blocks.append(UpBlock(
                in_c, skips, out_c, temb_c, config,
                attn=config.cross_attention_blocks[i], upsample=i != 0))
            in_c = out_c

        self.conv_norm_out = GroupNorm(min(config.norm_num_groups, ch[0]),
                                       ch[0])
        self.conv_out = Conv2D(ch[0], config.out_channels, 3, padding=1)

    def forward(self, sample, timestep, encoder_hidden_states):
        cfg = self.config
        temb = _timestep_embedding(timestep, self.time_proj_dim,
                                   cfg.freq_shift)
        temb = temb.astype(sample.dtype)
        temb = self.time_embedding[1](F.silu(self.time_embedding[0](temb)))

        x = self.conv_in(sample)
        skips = [x]
        for blk in self.down_blocks:
            x, s = blk(x, temb, encoder_hidden_states)
            skips.extend(s)
        x = self.mid_block(x, temb, encoder_hidden_states)
        for blk in self.up_blocks:
            n = len(blk.resnets)
            take, skips = skips[-n:], skips[:-n]
            x = blk(x, list(take), temb, encoder_hidden_states)
        return self.conv_out(F.silu(self.conv_norm_out(x)))


class UNetDenoiseLoss(Layer):
    """Epsilon-prediction MSE training objective (the standard SD denoising
    loss) — shared by bench.py and the tests so the objective is defined
    once."""

    def __init__(self, unet: UNet2DConditionModel):
        super().__init__()
        self.unet = unet

    def forward(self, latents, timesteps, encoder_hidden_states, noise):
        pred = self.unet(latents, timesteps, encoder_hidden_states)
        return F.mse_loss(pred, noise)
