"""Model exemplars.

The reference keeps models out-of-tree (PaddleNLP/PaddleFleetX); this package
ships the exemplars the north-star metric is measured on (BASELINE.json):
GPT-3 345M, Llama-2 7B/70B, an ERNIE-style MoE, and an SD UNet — plus
the BERT/ERNIE encoder family (MLM/NSP pretraining + classification).
"""

from .bert import (  # noqa: F401
    BertConfig, BertForMaskedLM, BertForPretraining,
    BertForSequenceClassification, BertModel, BertPretrainingCriterion,
    ErnieModel,
)
from .gpt import (  # noqa: F401
    GPTConfig, GPTForCausalLM, GPTForCausalLMPipe, GPTModel,
    GPTPretrainingCriterion,
)
from .llama import (LlamaConfig, LlamaForCausalLM,  # noqa: F401
                    LlamaForCausalLMPipe, LlamaModel, annotate_llama_tp)
from .moe_gpt import MoEGPTConfig, MoEGPTForCausalLM  # noqa: F401
from .unet import (  # noqa: F401
    UNet2DConditionModel, UNetConfig, UNetDenoiseLoss,
)
