"""ERNIE-style MoE GPT exemplar: GPT blocks whose FFN is a mixture of
experts on alternating layers (the reference measures MoE through
ERNIE-3.0-style models trained with
python/paddle/incubate/distributed/models/moe/MoELayer — SURVEY.md §2.2).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from .. import ops
from ..incubate.distributed.models.moe import MoELayer
from ..nn import functional as F
from ..nn.layer import Layer, LayerList
from ..nn.layers.common import Dropout, Embedding, LayerNorm
from .gpt import GPTBlock, GPTConfig, GPTSelfAttention


@dataclasses.dataclass
class MoEGPTConfig(GPTConfig):
    num_experts: int = 8
    top_k: int = 2
    moe_every: int = 2           # every Nth block uses MoE FFN
    capacity_factor: float = 1.2
    aux_loss_weight: float = 0.01
    expert_axis: Optional[str] = None   # mesh axis for EP (e.g. "dp")

    @staticmethod
    def tiny(**kw):
        d = dict(vocab_size=512, hidden_size=64, num_hidden_layers=4,
                 num_attention_heads=4, max_position_embeddings=128,
                 num_experts=4)
        d.update(kw)
        return MoEGPTConfig(**d)


class MoEGPTBlock(Layer):
    def __init__(self, config: MoEGPTConfig):
        super().__init__()
        self.ln_1 = LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.attn = GPTSelfAttention(config)
        self.ln_2 = LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.moe = MoELayer(
            d_model=config.hidden_size, num_expert=config.num_experts,
            d_hidden=config.intermediate_size, top_k=config.top_k,
            gate="gshard", capacity_factor=config.capacity_factor,
            expert_axis=config.expert_axis)
        self.drop = Dropout(config.hidden_dropout_prob)

    def forward(self, x, attn_mask=None):
        x = x + self.drop(self.attn(self.ln_1(x), attn_mask))
        x = x + self.drop(self.moe(self.ln_2(x)))
        return x


class MoEGPTForCausalLM(Layer):
    """GPT causal LM with MoE FFNs; ``total_aux_loss`` collects the gate
    losses of every MoE block for the training loss."""

    def __init__(self, config: MoEGPTConfig):
        super().__init__()
        self.config = config
        from ..nn import initializer as I
        from ..nn.param_attr import ParamAttr
        init = I.Normal(0.0, config.initializer_range)
        self.wte = Embedding(config.vocab_size, config.hidden_size,
                             weight_attr=ParamAttr(initializer=init))
        self.wpe = Embedding(config.max_position_embeddings, config.hidden_size,
                             weight_attr=ParamAttr(initializer=init))
        self.drop = Dropout(config.hidden_dropout_prob)
        blocks = []
        for i in range(config.num_hidden_layers):
            if config.moe_every and (i + 1) % config.moe_every == 0:
                blocks.append(MoEGPTBlock(config))
            else:
                blocks.append(GPTBlock(config))
        self.h = LayerList(blocks)
        self.ln_f = LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)

    def total_aux_loss(self):
        total = None
        for b in self.h:
            gate = getattr(getattr(b, "moe", None), "gate", None)
            if gate is not None and gate.has_loss:
                l = gate.get_loss()
                total = l if total is None else total + l
        return total

    def forward(self, input_ids, labels=None, attn_mask=None):
        b, s = input_ids.shape
        pos = ops.arange(s, dtype="int64").unsqueeze(0)
        x = self.drop(self.wte(input_ids) + self.wpe(pos))
        for block in self.h:
            x = block(x, attn_mask)
        hidden = self.ln_f(x)
        logits = ops.matmul(hidden, self.wte.weight, transpose_y=True)
        if labels is None:
            return logits
        loss = F.cross_entropy(
            logits.reshape([-1, self.config.vocab_size]),
            labels.reshape([-1]), reduction="mean")
        aux = self.total_aux_loss()
        if aux is not None:
            loss = loss + self.config.aux_loss_weight * aux
        return loss
