"""Llama exemplar (the north-star model: Llama-2 7B / 70B).

RMSNorm + rotary + GQA + SwiGLU, built from paddle_tpu.nn layers. Attention
and norms dispatch to the Pallas kernels via the incubate fused surface.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

from .. import ops
from ..generation import GenerationMixin
from ..incubate.nn import functional as FF
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer, LayerList
from ..nn.layers.common import Embedding, Linear, RMSNorm
from ..nn.param_attr import ParamAttr


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: Optional[int] = None
    intermediate_size: int = 11008
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    initializer_range: float = 0.02
    tie_word_embeddings: bool = False

    def __post_init__(self):
        if self.num_key_value_heads is None:
            self.num_key_value_heads = self.num_attention_heads

    @staticmethod
    def llama2_7b() -> "LlamaConfig":
        return LlamaConfig()

    @staticmethod
    def llama2_70b() -> "LlamaConfig":
        return LlamaConfig(hidden_size=8192, num_hidden_layers=80,
                           num_attention_heads=64, num_key_value_heads=8,
                           intermediate_size=28672)

    @staticmethod
    def tiny() -> "LlamaConfig":
        return LlamaConfig(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                           num_attention_heads=4, num_key_value_heads=2,
                           intermediate_size=128, max_position_embeddings=128)

    def num_params(self) -> int:
        h, l = self.hidden_size, self.num_hidden_layers
        kv = self.num_key_value_heads * (h // self.num_attention_heads)
        per_layer = h * h + 2 * h * kv + h * h          # q, k, v, o
        per_layer += 3 * h * self.intermediate_size      # gate, up, down
        per_layer += 2 * h                               # norms
        emb = self.vocab_size * h
        head = 0 if self.tie_word_embeddings else self.vocab_size * h
        return l * per_layer + emb + head + h


class LlamaAttention(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        h = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        self.head_dim = h // self.num_heads
        self.rope_theta = config.rope_theta
        init = ParamAttr(initializer=I.Normal(0.0, config.initializer_range))
        self.q_proj = Linear(h, self.num_heads * self.head_dim, weight_attr=init, bias_attr=False)
        self.k_proj = Linear(h, self.num_kv_heads * self.head_dim, weight_attr=init, bias_attr=False)
        self.v_proj = Linear(h, self.num_kv_heads * self.head_dim, weight_attr=init, bias_attr=False)
        self.o_proj = Linear(self.num_heads * self.head_dim, h, weight_attr=init, bias_attr=False)

    def forward(self, x, attn_mask=None, position_ids=None, cache=None):
        from ..kernels.paged_attention import is_paged_state

        b, s, _ = x.shape
        q = self.q_proj(x).reshape([b, s, self.num_heads, self.head_dim])
        k = self.k_proj(x).reshape([b, s, self.num_kv_heads, self.head_dim])
        v = self.v_proj(x).reshape([b, s, self.num_kv_heads, self.head_dim])
        paged = cache is not None and is_paged_state(cache[0])
        if cache is not None and position_ids is None:
            if paged:
                from ..kernels.paged_attention import paged_position_ids
                position_ids = paged_position_ids(s, cache[1], cache[0],
                                                  "int32")
            else:
                position_ids = (ops.arange(s, dtype="int32")
                                + cache[2]).unsqueeze(0)
        q, k, _ = FF.fused_rotary_position_embedding(
            q, k, None, position_ids=position_ids, rotary_emb_base=self.rope_theta)
        if paged:
            state, _offset = cache
            out, state = F.paged_scaled_dot_product_attention(q, k, v, state)
            return self.o_proj(out.reshape(
                [b, s, self.num_heads * self.head_dim])), state
        if cache is not None:
            k_cache, v_cache, offset = cache
            out, k_cache, v_cache = F.cached_scaled_dot_product_attention(
                q, k, v, k_cache, v_cache, offset)
            out = self.o_proj(
                out.reshape([b, s, self.num_heads * self.head_dim]))
            return out, (k_cache, v_cache)
        # GQA: kv stays UNEXPANDED — sdpa's flash path reads it at Hkv
        # bandwidth via GQA index maps; only the dense fallback expands.
        # NB the group layout differs: sdpa groups q heads contiguously
        # (head h -> kv head h // rep), matching repeat_interleave.
        out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                             is_causal=attn_mask is None,
                                             training=self.training)
        return self.o_proj(out.reshape([b, s, self.num_heads * self.head_dim]))


class LlamaMLP(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        init = ParamAttr(initializer=I.Normal(0.0, config.initializer_range))
        self.gate_proj = Linear(config.hidden_size, config.intermediate_size,
                                weight_attr=init, bias_attr=False)
        self.up_proj = Linear(config.hidden_size, config.intermediate_size,
                              weight_attr=init, bias_attr=False)
        self.down_proj = Linear(config.intermediate_size, config.hidden_size,
                                weight_attr=init, bias_attr=False)

    def forward(self, x):
        return self.down_proj(F.swiglu(self.gate_proj(x), self.up_proj(x)))


class LlamaDecoderLayer(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.input_layernorm = RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = RMSNorm(config.hidden_size,
                                                epsilon=config.rms_norm_eps)
        self.mlp = LlamaMLP(config)

    def forward(self, x, attn_mask=None, position_ids=None, cache=None):
        if cache is not None:
            attn, new_cache = self.self_attn(
                self.input_layernorm(x), attn_mask, position_ids, cache)
            x = x + attn
            x = x + self.mlp(self.post_attention_layernorm(x))
            return x, new_cache
        x = x + self.self_attn(self.input_layernorm(x), attn_mask, position_ids)
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x


class LlamaModel(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = Embedding(
            config.vocab_size, config.hidden_size,
            weight_attr=ParamAttr(initializer=I.Normal(0.0, config.initializer_range)))
        self.layers = LayerList([LlamaDecoderLayer(config)
                                 for _ in range(config.num_hidden_layers)])
        self.norm = RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)

    def forward(self, input_ids, attn_mask=None, position_ids=None,
                caches=None, offset=None):
        x = self.embed_tokens(input_ids)
        if caches is not None:
            from ..kernels.paged_attention import is_paged_state
            new_caches = []
            for layer, entry in zip(self.layers, caches):
                if is_paged_state(entry):
                    x, nc = layer(x, attn_mask, position_ids,
                                  cache=(entry, offset))
                else:
                    kc, vc = entry
                    x, nc = layer(x, attn_mask, position_ids,
                                  cache=(kc, vc, offset))
                new_caches.append(nc)
            return self.norm(x), new_caches
        for layer in self.layers:
            x = layer(x, attn_mask, position_ids)
        return self.norm(x)


class LlamaForCausalLM(GenerationMixin, Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  weight_attr=ParamAttr(
                                      initializer=I.Normal(0.0, config.initializer_range)),
                                  bias_attr=False)

    def logits(self, hidden):
        if self.lm_head is None:
            return ops.matmul(hidden, self.llama.embed_tokens.weight, transpose_y=True)
        return self.lm_head(hidden)

    def forward(self, input_ids, labels=None, attn_mask=None, position_ids=None):
        hidden = self.llama(input_ids, attn_mask, position_ids)
        if labels is None:
            return self.logits(hidden)
        # chunked fused LM loss: never materializes (tokens, vocab) f32
        from ..incubate.nn import functional as IF
        if self.lm_head is None:
            return IF.fused_linear_cross_entropy(
                hidden, self.llama.embed_tokens.weight, labels,
                transpose_y=True)
        return IF.fused_linear_cross_entropy(
            hidden, self.lm_head.weight, labels, transpose_y=False)

    # ---- decode path (GenerationMixin hooks) -----------------------------
    def cache_spec(self):
        c = self.config
        return [(c.num_key_value_heads, c.hidden_size // c.num_attention_heads)
                for _ in range(c.num_hidden_layers)]

    def forward_with_cache(self, input_ids, caches, offset):
        hidden, new_caches = self.llama(input_ids, caches=caches,
                                        offset=offset)
        return self.logits(hidden), new_caches

    def block_decode_spec(self, fused_layers: int = 1):
        """Per-layer weight layout for the fused block-decode serving
        path (kernels/fused_block_decode.py): which named parameters form
        each layer's BlockDecodeWeights, plus the embedding / final-norm
        / lm-head names and the attention geometry. The serving engine
        builds its ONE compiled decode step from this — the model's
        python forward never runs on the decode hot path.

        ``fused_layers=N`` (FLAGS_fused_block_layers) additionally
        publishes ``layer_groups`` — consecutive layer indices batched N
        per group (final group ragged) — for the multi-layer kernel: the
        engine stacks each group's BlockDecodeWeights into one
        MultiBlockDecodeWeights (q|k|v and gate|up merged into single
        wider matmuls) and runs the whole group in ONE pallas_call. The
        per-layer ``layers`` list is unchanged either way, so existing
        consumers (chunk prefill, spec-decode draft) never re-derive."""
        c = self.config
        layers = []
        for i in range(c.num_hidden_layers):
            p = f"llama.layers.{i}."
            layers.append(dict(
                ln1=p + "input_layernorm.weight",
                wq=p + "self_attn.q_proj.weight",
                wk=p + "self_attn.k_proj.weight",
                wv=p + "self_attn.v_proj.weight",
                wo=p + "self_attn.o_proj.weight",
                ln2=p + "post_attention_layernorm.weight",
                wg=p + "mlp.gate_proj.weight",
                wu=p + "mlp.up_proj.weight",
                wd=p + "mlp.down_proj.weight"))
        spec = dict(
            arch="llama", layers=layers,
            embed="llama.embed_tokens.weight",
            final_norm="llama.norm.weight",
            lm_head=None if self.lm_head is None else "lm_head.weight",
            num_heads=c.num_attention_heads,
            num_kv_heads=c.num_key_value_heads,
            rope_theta=c.rope_theta,
            epsilon=c.rms_norm_eps)
        if fused_layers > 1:
            n = c.num_hidden_layers
            spec["layer_groups"] = [
                list(range(i, min(i + int(fused_layers), n)))
                for i in range(0, n, int(fused_layers))]
        return spec


# ===================================================== pipeline-parallel pipe
class LlamaEmbeddingPipe(Layer):
    """First pipeline entry: token embedding (rotary needs no position
    table). Reference: PaddleNLP LlamaForCausalLMPipe's embedding stage."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.embed_tokens = Embedding(
            config.vocab_size, config.hidden_size,
            weight_attr=ParamAttr(
                initializer=I.Normal(0.0, config.initializer_range)))

    def forward(self, input_ids):
        return self.embed_tokens(input_ids)


class LlamaPretrainingCriterion(Layer):
    """loss_fn for the pipe model: mean CE over all tokens."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.vocab_size = config.vocab_size

    def forward(self, logits, labels):
        return F.cross_entropy(logits.reshape([-1, self.vocab_size]),
                               labels.reshape([-1]), reduction="mean")


# Megatron TP layout for the Llama weights (Linear weights are (in, out)):
# column-parallel splits the output dim, row-parallel the input dim.
_LLAMA_TP_COLUMN = ("q_proj.weight", "k_proj.weight", "v_proj.weight",
                    "gate_proj.weight", "up_proj.weight")
_LLAMA_TP_ROW = ("o_proj.weight", "down_proj.weight")


def annotate_llama_tp(layer: Layer, axis: str = "mp") -> None:
    """Attach Megatron TP ``dist_attr`` PartitionSpecs to a Llama(-pipe)
    layer tree by parameter role. PipelineTrainStep / hapi.TrainStep read
    ``dist_attr`` when building param shardings (reference: the
    Column/RowParallelLinear layout of
    python/paddle/distributed/fleet/layers/mpu/mp_layers.py, applied as
    GSPMD annotations instead of explicit collectives)."""
    from jax.sharding import PartitionSpec as P
    for name, p in layer.named_parameters():
        if any(name.endswith(s) for s in _LLAMA_TP_COLUMN):
            p.dist_attr = P(None, axis)
        elif any(name.endswith(s) for s in _LLAMA_TP_ROW):
            p.dist_attr = P(axis, None)
        elif name.endswith("embed_tokens.weight"):
            p.dist_attr = P(axis, None)       # vocab-sharded embedding
        elif name.endswith("lm_head.weight"):
            p.dist_attr = P(None, axis)       # vocab-sharded head


def LlamaForCausalLMPipe(config: LlamaConfig,
                         num_stages: Optional[int] = None,
                         topology=None, seg_method: str = "layer:LlamaDecoderLayer",
                         recompute_interval: int = 0,
                         tensor_parallel: bool = False,
                         tensor_parallel_axis: str = "mp"):
    """The pipeline-parallel Llama exemplar (reference: PaddleNLP
    LlamaForCausalLMPipe over the reference's PipelineLayer machinery,
    SURVEY.md §2.2 meta_parallel PP). The uniform LlamaDecoderLayer region
    is stacked over the pp mesh axis by PipelineTrainStep;
    ``tensor_parallel=True`` additionally attaches the Megatron TP layout
    as dist_attr annotations."""
    from ..distributed.fleet.meta_parallel.pp_layers import (
        LayerDesc, PipelineLayer)
    from ..nn.layers.common import RMSNorm as _RMSNorm

    descs = [LayerDesc(LlamaEmbeddingPipe, config)]
    descs += [LayerDesc(LlamaDecoderLayer, config)
              for _ in range(config.num_hidden_layers)]
    descs.append(LayerDesc(_RMSNorm, config.hidden_size,
                           epsilon=config.rms_norm_eps))
    descs.append(LayerDesc(Linear, config.hidden_size, config.vocab_size,
                           bias_attr=False))
    pipe = PipelineLayer(
        descs, num_stages=num_stages, topology=topology,
        loss_fn=LlamaPretrainingCriterion(config), seg_method=seg_method,
        recompute_interval=recompute_interval)
    if tensor_parallel:
        from jax.sharding import PartitionSpec as P
        annotate_llama_tp(pipe, tensor_parallel_axis)
        # the head is the Linear we appended last: column-parallel vocab
        head = pipe.run_function[-1]
        assert isinstance(head, Linear), head
        head.weight.dist_attr = P(None, tensor_parallel_axis)
    return pipe
