"""Pipeline-parallel Llama with the zero-bubble (ZBH1) schedule.

Run on the CPU-simulated 8-device mesh:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/train_pipeline_zbh1.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _env import ensure_backend
ensure_backend()

import numpy as np


def main():
    import jax
    from jax.sharding import Mesh

    import paddle_tpu as paddle
    from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel \
        import PipelineTrainStep
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLMPipe

    if len(jax.devices()) < 8:
        sys.exit("need 8 devices: run with JAX_PLATFORMS=cpu "
                 "XLA_FLAGS=--xla_force_host_platform_device_count=8")

    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=256, hidden_size=64, num_hidden_layers=4,
                      num_attention_heads=4, num_key_value_heads=2,
                      intermediate_size=128, max_position_embeddings=128)
    pipe = LlamaForCausalLMPipe(cfg, num_stages=4)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("dp", "pp"))
    step = PipelineTrainStep(
        pipe, paddle.optimizer.AdamW(1e-3, parameters=pipe.parameters()),
        mesh, num_microbatches=4, schedule="zbh1")
    print("mesh: dp=2 x pp=4, schedule=zbh1")

    rng = np.random.default_rng(0)
    for i in range(5):
        ids = rng.integers(0, cfg.vocab_size, (8, 33))
        loss = step(paddle.to_tensor(ids[:, :-1].astype(np.int32)),
                    paddle.to_tensor(ids[:, 1:].astype(np.int32)))
        print(f"step {i}  loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
