"""Train → export → serve: every deployment surface over ONE artifact.

Run (CPU): JAX_PLATFORMS=cpu python examples/deploy_inference.py
Run (TPU): python examples/deploy_inference.py

Mirrors the reference deployment story (train dygraph → jit.save /
save_inference_model → Predictor or static Executor):

  1. train a small model eagerly;
  2. export it THREE reference ways — ``paddle.jit.save`` (dygraph
     path), ``paddle.static.save_inference_model`` (static Program
     path), and a weight-only-int8 variant of the serving matmul;
  3. serve the artifact through ``paddle.jit.load``, the
     ``paddle.inference`` Predictor (with and without the ir_optim
     pass), and the classic ``load_inference_model`` + ``Executor.run``
     loop — all agreeing numerically.
"""
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _env import ensure_backend
ensure_backend()

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import inference as paddle_infer
from paddle_tpu.static import InputSpec


def main():
    rng = np.random.default_rng(0)
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(16, 64), nn.GELU(), nn.Linear(64, 8))
    opt = paddle.optimizer.AdamW(1e-2, parameters=net.parameters())
    xs = rng.standard_normal((64, 16)).astype(np.float32)
    ys = rng.standard_normal((64, 8)).astype(np.float32)
    for step in range(30):
        loss = F.mse_loss(net(paddle.to_tensor(xs)), paddle.to_tensor(ys))
        loss.backward()
        opt.step()
        opt.clear_grad()
    print(f"trained: loss={float(loss):.4f}")

    workdir = tempfile.mkdtemp()
    x = rng.standard_normal((5, 16)).astype(np.float32)
    ref = net(paddle.to_tensor(x)).numpy()

    # -- export 1: dygraph jit.save ---------------------------------------
    dy_prefix = os.path.join(workdir, "dygraph_model")
    paddle.jit.save(net, dy_prefix,
                    input_spec=[InputSpec([None, 16], "float32", name="x")])
    loaded = paddle.jit.load(dy_prefix)
    np.testing.assert_allclose(loaded(x).numpy(), ref, rtol=1e-5, atol=1e-6)
    print("jit.save -> jit.load OK")

    # -- export 2: static Program -> save_inference_model ------------------
    st_prefix = os.path.join(workdir, "static_model")
    main_prog = paddle.static.Program()
    startup = paddle.static.Program()
    with paddle.static.program_guard(main_prog, startup):
        xv = paddle.static.data("x", [None, 16], "float32")
        out = net(xv)
    paddle.static.save_inference_model(st_prefix, [xv], [out],
                                       program=main_prog)
    exe = paddle.static.Executor()
    prog, feed_names, fetches = paddle.static.load_inference_model(
        st_prefix, exe)
    (got,) = exe.run(prog, feed={"x": x}, fetch_list=fetches)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
    print(f"save_inference_model -> Executor.run OK (feeds={feed_names})")

    # -- serve: the Predictor facade, ir_optim on vs off -------------------
    def serve(prefix, ir_optim):
        config = paddle_infer.Config(prefix)
        config.switch_ir_optim(ir_optim)
        pred = paddle_infer.create_predictor(config)
        pred.run([x])                                # warm / compile
        t0 = time.perf_counter()
        for _ in range(50):
            out = pred.run([x])[0]
        return out, (time.perf_counter() - t0) / 50

    out_opt, t_opt = serve(dy_prefix, True)
    out_raw, t_raw = serve(dy_prefix, False)
    np.testing.assert_allclose(out_opt, ref, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(out_raw, ref, rtol=1e-5, atol=1e-6)
    print(f"Predictor OK: ir_optim on {t_opt*1e6:.0f}us/req, "
          f"off {t_raw*1e6:.0f}us/req ({t_raw/t_opt:.1f}x)")

    # -- weight-only int8 serving matmul ----------------------------------
    from paddle_tpu.nn import quant
    w1 = net[2].weight
    qw, scale = quant.weight_quantize(w1)
    hidden = F.gelu(net[0](paddle.to_tensor(x)))
    q_out = quant.weight_only_linear(hidden, qw, bias=net[2].bias,
                                     weight_scale=scale)
    err = np.abs(q_out.numpy() - ref).max() / (np.abs(ref).max() + 1e-9)
    print(f"weight-only int8 serving OK: rel err {err:.4f}")
    assert err < 0.05
    print("ALL DEPLOY PATHS OK")


if __name__ == "__main__":
    main()
