"""Continuous-batching serving: requests admit mid-decode, pages recycle.

Run: JAX_PLATFORMS=cpu python examples/serve_engine.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _env import ensure_backend
ensure_backend()

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.generation.serving import ServingEngine
from paddle_tpu.models import GPTConfig, GPTForCausalLM


def main():
    paddle.seed(0)
    cfg = GPTConfig.tiny()
    model = GPTForCausalLM(cfg)
    rng = np.random.default_rng(0)

    eng = ServingEngine(model, max_batch=2, page_size=8, max_seq_len=64)

    # four requests, two slots: admission is continuous — r2/r3 enter the
    # moment earlier requests finish and return their pages
    prompts = [rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)
               for n in (6, 10, 4, 8)]
    rids = [eng.submit(p, max_new_tokens=6) for p in prompts]
    steps = 0
    while eng.has_work():
        eng.step()
        steps += 1
        active = sum(s is not None for s in eng._slots)
        print(f"step {steps:2d}: active slots={active} "
              f"free pages={eng.pool.free_page_count()}")
    results = eng.run()

    for rid, prompt in zip(rids, prompts):
        solo = model.generate(
            paddle.to_tensor(prompt[None]), max_new_tokens=6,
            do_sample=False, return_full_sequence=False).numpy()[0].tolist()
        assert results[rid] == solo
        print(f"request {rid}: {results[rid]}  (== solo greedy)")

    # ---- automatic prefix caching: a shared system prompt is prefilled
    # ONCE; later requests adopt its pages read-only (copy-on-write pool)
    eng2 = ServingEngine(model, max_batch=2, page_size=8, max_seq_len=64,
                         prefix_cache=True)
    system = rng.integers(0, cfg.vocab_size, (16,)).astype(np.int32)
    users = [rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)
             for n in (3, 5, 4)]
    for i, u in enumerate(users):
        p = np.concatenate([system, u]).astype(np.int32)
        rid = eng2.submit(p, max_new_tokens=5)
        out = eng2.run()[rid]
        solo = model.generate(
            paddle.to_tensor(p[None]), max_new_tokens=5,
            do_sample=False, return_full_sequence=False).numpy()[0].tolist()
        assert out == solo
        hit = eng2._prefix.lookup(p)[1]
        print(f"prefix-cache request {i}: cached prefix {hit} tokens, "
              f"tokens {out}  (== solo greedy)")


if __name__ == "__main__":
    main()
