"""Single-host GPT pretraining with the jitted TrainStep.

Run (CPU mesh):   JAX_PLATFORMS=cpu python examples/train_gpt.py
Run (TPU chip):   python examples/train_gpt.py

Mirrors the reference's gpt pretrain loop (tools/train.py style): config,
synthetic data, AdamW + cosine LR + global-norm clip, AMP on TPU, a
checkpoint save/restore at the end.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _env import ensure_backend
ensure_backend()

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.hapi import TrainStep
from paddle_tpu.models import GPTConfig, GPTForCausalLM


def main():
    import jax

    on_tpu = paddle.flags.is_tpu_backend()
    cfg = GPTConfig.gpt3_345m() if on_tpu else GPTConfig.tiny()
    batch, seq, steps = (8, 1024, 50) if on_tpu else (4, 64, 20)

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")
    sched = paddle.optimizer.lr.CosineAnnealingDecay(1e-4, T_max=steps)
    opt = paddle.optimizer.AdamW(
        sched, parameters=model.parameters(), weight_decay=0.01,
        grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0),
        multi_precision=on_tpu)
    step = TrainStep(model, opt)

    rng = np.random.default_rng(0)
    for i in range(steps):
        ids = rng.integers(0, cfg.vocab_size, (batch, seq + 1))
        x = paddle.to_tensor(ids[:, :-1].astype(np.int32))
        y = paddle.to_tensor(ids[:, 1:].astype(np.int32))
        loss = step(x, y)
        if i % 5 == 0 or i == steps - 1:
            print(f"step {i:3d}  loss {float(loss):.4f}  "
                  f"lr {opt.get_lr():.2e}")
        # NB: TrainStep steps the LR scheduler itself — do not also call
        # sched.step() here (it would run the schedule at 2x speed)

    step.sync_to_model()
    paddle.save(model.state_dict(), "/tmp/gpt_example.pdparams")
    model.set_state_dict(paddle.load("/tmp/gpt_example.pdparams"))
    print("checkpoint round-trip OK")


if __name__ == "__main__":
    main()
