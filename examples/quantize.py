"""Quantization workflows: QAT (train through fake quant), PTQ
(calibrate + convert), and direct weight-only conversion for serving.

Run: JAX_PLATFORMS=cpu python examples/quantize.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _env import ensure_backend
ensure_backend()

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.quantization import (PTQ, QAT, FakeQuanterWithAbsMaxObserver,
                                     QuantConfig)


def main():
    paddle.seed(0)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(32, 16).astype(np.float32))

    # --- QAT: straight-through fake quant, weights stay trainable
    model = nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 16))
    QAT(QuantConfig(activation=FakeQuanterWithAbsMaxObserver)).quantize(model)
    opt = paddle.optimizer.AdamW(5e-3, parameters=model.parameters())
    for i in range(30):
        loss = F.mse_loss(model(x), x)
        loss.backward(); opt.step(); opt.clear_grad()
    print(f"QAT: trained THROUGH int8 fake quant, final loss {float(loss):.4f}")

    # --- PTQ: observe calibration batches, convert to the int8 runtime
    model = nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 16))
    ref = model(x).numpy()
    ptq = PTQ(QuantConfig())
    ptq.quantize(model)
    for _ in range(4):
        model(paddle.to_tensor(rng.randn(32, 16).astype(np.float32)))
    ptq.convert(model)
    err = np.abs(model(x).numpy() - ref).max() / (np.abs(ref).max() + 1e-9)
    print(f"PTQ: converted to int8 QuantizedLinear, rel err {err:.4f}")

    # --- serving shortcut: direct weight-only conversion (no calibration)
    from paddle_tpu.nn.quant import quantize_linears
    model = nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 16))
    ref = model(x).numpy()
    quantize_linears(model, algo="weight_only_int8")
    err = np.abs(model(x).numpy() - ref).max() / (np.abs(ref).max() + 1e-9)
    print(f"weight-only int8: rel err {err:.4f} at half the weight bytes")


if __name__ == "__main__":
    main()
