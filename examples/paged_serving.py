"""Paged-KV-cache serving: shared page pool, block tables, page recycling.

The vLLM-style serving substrate (reference: block_multihead_attention):
requests draw cache pages from ONE shared pool and return them on
completion, so HBM holds ceil(len/page) pages per live request instead of
a max-length ring buffer each.

Run: JAX_PLATFORMS=cpu python examples/paged_serving.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _env import ensure_backend
ensure_backend()

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models import GPTConfig, GPTForCausalLM


def main():
    paddle.seed(0)
    cfg = GPTConfig.tiny()
    model = GPTForCausalLM(cfg)
    model.eval()
    rng = np.random.default_rng(0)

    # request 1: batch of two prompts decoding over a paged pool
    prompt = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (2, 7)).astype(np.int32))
    out = model.generate_paged(prompt, max_new_tokens=8, page_size=8)
    print("request 1:", out.numpy()[0].tolist())

    # the ring-buffer scan path produces the identical tokens
    ring = model.generate(prompt, max_new_tokens=8, do_sample=False)
    assert (out.numpy() == ring.numpy()).all()
    print("matches ring-buffer generate token-for-token")

    # page accounting: the pool-level API that a continuous-batching
    # scheduler drives directly (allocate/append/attend/free)
    from paddle_tpu.kernels.paged_attention import PagedKVCache
    import jax.numpy as jnp
    pool = PagedKVCache(num_layers=cfg.num_hidden_layers, num_pages=32,
                        page_size=8, num_kv_heads=cfg.num_attention_heads,
                        head_dim=cfg.hidden_size // cfg.num_attention_heads,
                        max_batch=4, max_seq_len=64, dtype=jnp.float32)
    pool.allocate(0, 30)
    print("after admit:   free pages =", pool.free_page_count())
    pool.free_sequence(0)
    print("after release: free pages =", pool.free_page_count())


if __name__ == "__main__":
    main()
