"""Parameter-server training: sparse embeddings on host-side table
servers, dense math on the worker.

Run: JAX_PLATFORMS=cpu python examples/train_ps.py

The classic recommendation-model deploy shape (reference: the brpc PS
under paddle/fluid/distributed/ps/ driven by
fleet.init(role)/init_server/run_server/init_worker/stop_worker):

  * this script re-launches itself twice as PSERVER processes via the
    TRAINING_ROLE env protocol, each hosting a shard of the embedding
    table (ids hash-partitioned id % n_servers);
  * the worker (this process) trains a tiny two-tower-ish CTR model:
    DistributedEmbedding rows pulled per batch + a dense MLP, labels
    from a synthetic click rule;
  * embedding grads are PUSHED to the servers (server-side Adagrad,
    fully async a_sync semantics); dense params train locally;
  * the first worker's fleet.stop_worker() shuts the servers down.
"""
import os
import socket
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _env import ensure_backend
ensure_backend()

import numpy as np


def free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


SERVER = """
import paddle_tpu.distributed.fleet as fleet
fleet.init(is_collective=False)
fleet.init_server()
print("SERVING", flush=True)
fleet.run_server()
"""


def main():
    ports = [free_port(), free_port()]
    eps = ",".join(f"127.0.0.1:{p}" for p in ports)
    servers = []
    for p in ports:
        env = dict(os.environ)
        env.update(TRAINING_ROLE="PSERVER", PADDLE_PSERVERS_IP_PORT_LIST=eps,
                   POD_IP="127.0.0.1", PADDLE_PORT=str(p),
                   JAX_PLATFORMS="cpu")
        env.pop("PALLAS_AXON_POOL_IPS", None)   # servers never touch jax
        env.pop("PALLAS_AXON_REMOTE_COMPILE", None)
        servers.append(subprocess.Popen([sys.executable, "-c", SERVER],
                                        env=env, stdout=subprocess.PIPE,
                                        text=True))
    for s in servers:
        assert s.stdout.readline().strip() == "SERVING"
    print(f"2 table servers up at {eps}")

    os.environ["TRAINING_ROLE"] = "TRAINER"
    os.environ["PADDLE_PSERVERS_IP_PORT_LIST"] = eps
    import paddle_tpu as paddle
    import paddle_tpu.distributed.fleet as fleet
    from paddle_tpu.distributed.ps import DistributedEmbedding

    fleet.init(is_collective=False)
    fleet.init_worker()

    vocab, dim = 10_000, 16
    emb = DistributedEmbedding(vocab, dim, optimizer="adagrad", lr=0.1,
                               seed=0)
    mlp = paddle.nn.Sequential(
        paddle.nn.Linear(3 * dim, 32), paddle.nn.ReLU(),
        paddle.nn.Linear(32, 1))
    opt = paddle.optimizer.AdamW(1e-2, parameters=mlp.parameters())

    rng = np.random.default_rng(0)
    losses = []
    for step in range(30):
        ids = rng.integers(0, vocab, (64, 3))
        # synthetic click rule: "user likes low ids"
        label = (ids.sum(1) < 1.5 * vocab).astype(np.float32)[:, None]
        feats = emb(paddle.to_tensor(ids))           # pulled from servers
        logits = mlp(feats.reshape([64, -1]))
        loss = paddle.nn.functional.binary_cross_entropy_with_logits(
            logits, paddle.to_tensor(label))
        loss.backward()                              # pushes row grads
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
        if step % 10 == 0:
            print(f"step {step:3d} loss {losses[-1]:.4f}")

    from paddle_tpu.distributed import ps
    stats = ps.the_client().stats()
    rows = sum(s[emb.table_id] for s in stats)
    print(f"final loss {losses[-1]:.4f} (from {losses[0]:.4f}); "
          f"{rows} rows live across {len(stats)} servers "
          f"{[s[emb.table_id] for s in stats]}")
    assert losses[-1] < losses[0]
    fleet.stop_worker()                              # shuts servers down
    for s in servers:
        assert s.wait(timeout=20) == 0
    print("servers shut down cleanly — PS lifecycle complete")


if __name__ == "__main__":
    main()
