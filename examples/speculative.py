"""Speculative decoding: a small draft proposes, the target verifies.

Greedy speculation is LOSSLESS — the output equals the target's own
greedy decode token for token; the win is wall-clock (up to gamma+1
tokens per target forward when the draft agrees).

Run: JAX_PLATFORMS=cpu python examples/speculative.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _env import ensure_backend
ensure_backend()

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models import GPTConfig, GPTForCausalLM


def main():
    paddle.seed(0)
    cfg = GPTConfig.tiny()
    target = GPTForCausalLM(cfg)
    # a cheaper draft: half width, one layer, same vocab
    paddle.seed(1)
    draft = GPTForCausalLM(GPTConfig(
        vocab_size=cfg.vocab_size, hidden_size=32, num_hidden_layers=1,
        num_attention_heads=2, max_position_embeddings=128))

    prompt = paddle.to_tensor(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (1, 8)).astype(np.int32))

    ref = target.generate(prompt, max_new_tokens=16, do_sample=False)
    spec = target.generate_speculative(prompt, draft, max_new_tokens=16,
                                       num_speculative_tokens=4)
    print("greedy     :", ref.numpy()[0, 8:].tolist())
    print("speculative:", spec.numpy()[0, 8:].tolist())
    assert (ref.numpy() == spec.numpy()).all()
    print("identical output — the draft only changes the SCHEDULE")


if __name__ == "__main__":
    main()
