"""Speculative decoding: a small draft proposes, the target verifies.

Greedy speculation is LOSSLESS — the output equals the target's own
greedy decode token for token; the win is wall-clock (up to gamma+1
tokens per target forward when the draft agrees).

Since r16 speculation is a first-class ServingEngine decode mode:
pass ``draft_model=`` and every admitted request speculates whenever
the decode-slot budget affords it (a speculating request prices as
gamma+1 slots, and gamma adapts per request to the observed accept
rate). The standalone ``generate_speculative`` loop is still shown
at the end for the single-request API.

Run: JAX_PLATFORMS=cpu python examples/speculative.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _env import ensure_backend
ensure_backend()

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.generation.serving import ServingEngine
from paddle_tpu.models import GPTConfig, GPTForCausalLM


def main():
    paddle.seed(0)
    cfg = GPTConfig.tiny()
    target = GPTForCausalLM(cfg)
    target.eval()
    # a cheaper draft: half width, one layer, same vocab
    paddle.seed(1)
    draft = GPTForCausalLM(GPTConfig(
        vocab_size=cfg.vocab_size, hidden_size=32, num_hidden_layers=1,
        num_attention_heads=2, max_position_embeddings=128))
    draft.eval()

    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)
               for _ in range(3)]

    # --- engine path: speculation as a decode MODE, not a loop
    plain = ServingEngine(target, max_batch=2, page_size=8,
                          max_seq_len=64)
    rids = [plain.submit(p, max_new_tokens=16) for p in prompts]
    ref = plain.run()

    spec = ServingEngine(target, max_batch=2, page_size=8,
                         max_seq_len=64, draft_model=draft)
    srids = [spec.submit(p, max_new_tokens=16) for p in prompts]
    out = spec.run()

    for rid, srid in zip(rids, srids):
        print("greedy     :", ref[rid])
        print("speculative:", out[srid])
        assert ref[rid] == out[srid]
    acc = spec.spec_tokens_accepted
    rej = spec.spec_tokens_rejected
    print(f"engine rounds={spec.spec_rounds} accepted={acc} "
          f"rejected={rej} (accept rate "
          f"{acc / max(1, acc + rej):.2f})")
    print("identical output — the draft only changes the SCHEDULE")

    # --- the single-request API is the same contract
    prompt = paddle.to_tensor(prompts[0][None])
    solo = target.generate(prompt, max_new_tokens=16, do_sample=False)
    assert solo.numpy()[0, 8:].tolist() == ref[rids[0]]
    spec1 = target.generate_speculative(prompt, draft, max_new_tokens=16,
                                        num_speculative_tokens=4)
    assert (solo.numpy() == spec1.numpy()).all()
    print("generate_speculative agrees with the engine path")


if __name__ == "__main__":
    main()
