"""KV-cache decoding with the jitted generate() loop.

Run: JAX_PLATFORMS=cpu python examples/generate.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _env import ensure_backend
ensure_backend()

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM


def main():
    paddle.seed(0)
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    model.eval()
    prompt = paddle.to_tensor(
        np.random.default_rng(0).integers(0, cfg.vocab_size,
                                          (2, 8)).astype(np.int32))
    out = model.generate(prompt, max_new_tokens=24, do_sample=False)
    print("greedy :", out.numpy()[0][:16].tolist(), "...")
    out = model.generate(prompt, max_new_tokens=24, do_sample=True,
                         top_k=8, temperature=0.9)
    print("sampled:", out.numpy()[0][:16].tolist(), "...")
    out = model.generate(prompt, max_new_tokens=24, num_beams=4,
                         length_penalty=0.8)
    print("beam-4 :", out.numpy()[0][:16].tolist(), "...")
    out = model.generate(prompt, max_new_tokens=24, do_sample=False,
                         repetition_penalty=1.3)
    print("penalty:", out.numpy()[0][:16].tolist(), "...")

    # weight-only int8 serving: half the weight bytes per decode step
    from paddle_tpu.nn.quant import quantize_linears
    quantize_linears(model)
    out = model.generate(prompt, max_new_tokens=24, do_sample=False)
    print("int8   :", out.numpy()[0][:16].tolist(), "...")


if __name__ == "__main__":
    main()
