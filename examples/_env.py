"""Shared example bootstrap: on machines with the axon TPU tunnel plugin,
a CPU-pinned run must drop the plugin env BEFORE python imports jax (the
sitecustomize registers a backend whose init can hang when the tunnel is
down). Call first thing; re-execs the script once with a clean env."""
import os
import sys


def ensure_backend():
    if (os.environ.get("JAX_PLATFORMS", "") == "cpu"
            and "PALLAS_AXON_POOL_IPS" in os.environ
            and os.environ.get("_EXAMPLE_ENV_CLEAN") != "1"):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("PALLAS_AXON_REMOTE_COMPILE", None)
        env["_EXAMPLE_ENV_CLEAN"] = "1"
        os.execve(sys.executable, [sys.executable] + sys.argv, env)
