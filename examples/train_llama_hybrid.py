"""Hybrid-parallel Llama pretraining: dp x mp (TP) via the fleet API.

Run on the CPU-simulated 8-device mesh:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/train_llama_hybrid.py

The same script runs unchanged on a real TPU slice — the mesh comes from
the hybrid topology, the shardings from the Megatron dist_attr
annotations, and XLA inserts the collectives (GSPMD).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _env import ensure_backend
ensure_backend()

import numpy as np


def main():
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.distributed.fleet.base_topology import (
        create_hybrid_communicate_group)
    from paddle_tpu.hapi import TrainStep
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.llama import annotate_llama_tp

    n = len(jax.devices())
    mp = 2 if n % 2 == 0 else 1
    dp = n // mp
    hcg = create_hybrid_communicate_group(dp_degree=dp, mp_degree=mp)
    mesh = hcg.get_mesh()
    print(f"mesh: dp={dp} x mp={mp} over {n} devices")

    paddle.seed(0)
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    annotate_llama_tp(model)           # Megatron TP layout as dist_attr
    opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters())
    step = TrainStep(model, opt, mesh=mesh, data_axes=("dp",))

    rng = np.random.default_rng(0)
    batch = 2 * dp
    for i in range(10):
        ids = rng.integers(0, cfg.vocab_size, (batch, 33))
        loss = step(paddle.to_tensor(ids[:, :-1].astype(np.int32)),
                    paddle.to_tensor(ids[:, 1:].astype(np.int32)))
        print(f"step {i}  loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
