"""Fused-vs-unfused transformer-block decode A/B on the serving engine.

Measures the ONE number the fused_block_decode work exists for: the
steady-state per-step latency of `ServingEngine.step()` with the fused
one-kernel-per-layer program (FLAGS_fused_block_decode=1,
kernels/fused_block_decode.py) against the generic op-chain step
(FLAGS_fused_block_decode=0), same model, same batch, same backend — plus
the decode program cache's trace counts, asserting the zero-retrace
contract holds over the whole run.

Emits one JSON line per phase and a FINAL line in the standard bench.py
schema ({"metric", "value", "unit", "vs_baseline", ...}) so the sprint
harness banks it into the BENCH_*.json ledger unchanged:

    value        = fused steady-state step time, ms
    vs_baseline  = unfused_step_ms / fused_step_ms (the speedup; >= 1.0
                   is the acceptance bar "fused <= unfused")

Timing follows bench.py's decode protocol: compile on the first step,
then wall-clock the drain loop (each step() host-syncs by pulling the
argmax tokens). Test mode (CHIP_SPRINT_TEST=1): LlamaConfig.tiny() on
CPU validates plumbing + schema.

r17 adds the cross-layer N-sweep: the same A/B repeated at
FLAGS_fused_block_layers=N for each N in FUSED_BENCH_NLAYERS (default
"1,2,4" — N=1 is the per-layer fused kernel, N>1 the grouped
one-pallas_call-per-N-layers program; on CPU both run their pure-jnp
refs, so the sweep is an apples-to-apples program-structure A/B on any
backend). The FINAL row carries ``nlayer_sweep`` ({N: step_ms}) and
``nlayer_ok`` (best grouped step <= per-layer step AND zero retraces at
every rung) — banked as FUSED_DECODE_BENCH_r17.json.

Env knobs: FUSED_BENCH_MODEL (llama_tiny|llama2_7b), BENCH_DECODE_TOKENS,
BENCH_DECODE_BATCH, BENCH_PROMPT_LEN, FUSED_BENCH_NLAYERS.
"""
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_BACKEND = "unknown"
BENCH_SCHEMA = 1


def emit(d: dict) -> None:
    d.setdefault("backend", _BACKEND)
    print(json.dumps(d), flush=True)


def main() -> int:
    import numpy as np

    import jax

    import paddle_tpu as paddle
    from paddle_tpu import flags
    from paddle_tpu.flags import is_tpu_backend
    from paddle_tpu.generation.program_cache import decode_program_cache
    from paddle_tpu.generation.serving import ServingEngine
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    global _BACKEND
    _BACKEND = jax.default_backend()
    test_mode = (os.environ.get("CHIP_SPRINT_TEST") == "1"
                 or not is_tpu_backend())
    name = os.environ.get("FUSED_BENCH_MODEL",
                          "llama_tiny" if test_mode else "llama2_7b")
    if name == "llama_tiny":
        cfg = LlamaConfig.tiny()
    elif name == "llama_small":
        # CPU A/B workhorse for the N-sweep: ~30x the matmul work of
        # tiny per step, so the grouped-vs-per-layer program delta rises
        # above the engine's fixed host overhead; 4 layers lets N=4 form
        # a single full group
        cfg = LlamaConfig(vocab_size=1024, hidden_size=256,
                          num_hidden_layers=4, num_attention_heads=8,
                          num_key_value_heads=4, intermediate_size=512,
                          max_position_embeddings=256)
    else:
        cfg = LlamaConfig.llama2_7b()
    batch = int(os.environ.get("BENCH_DECODE_BATCH", "4"))
    steps = int(os.environ.get("BENCH_DECODE_TOKENS",
                               "16" if name.startswith("llama_t") else "64"))
    prompt_len = int(os.environ.get("BENCH_PROMPT_LEN",
                                    "24" if name == "llama_tiny" else "128"))
    page = 8 if name == "llama_tiny" else 64
    max_seq = prompt_len + steps + page

    emit({"phase": "init", "model": name, "batch": batch,
          "decode_tokens": steps, "prompt_len": prompt_len})

    t0 = time.perf_counter()
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if is_tpu_backend():
        model.to(dtype="bfloat16")
    model.eval()
    emit({"phase": "build", "s": round(time.perf_counter() - t0, 2),
          "n_params": cfg.num_params()})

    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, (prompt_len,))
               .astype(np.int32) for _ in range(batch)]

    def run(fused: bool, nlayers: int = 1) -> dict:
        flags.set_flags({"fused_block_decode": fused,
                         "fused_block_layers": nlayers})
        eng = ServingEngine(model, max_batch=batch, page_size=page,
                            max_seq_len=max_seq)
        for p in prompts:
            eng.submit(p, steps)
        t_compile = time.perf_counter()
        eng.step()                    # prefills + first decode: compiles
        compile_s = time.perf_counter() - t_compile
        traces_before = decode_program_cache().trace_count(eng.decode_key)
        n = 0
        t0 = time.perf_counter()
        while eng.has_work():
            eng.step()                # host-syncs on the argmax pull
            n += 1
        wall = time.perf_counter() - t0
        traces = decode_program_cache().trace_count(eng.decode_key)
        return {"kind": eng.decode_key.kind,
                "step_ms": round(wall / max(n, 1) * 1000, 3),
                "steps_timed": n,
                "first_step_s": round(compile_s, 3),
                "tokens_per_sec": round(batch * n / wall, 1) if wall else None,
                "traces": traces,
                "retraces_during_run": traces - traces_before}

    sweep_ns = [int(s) for s in os.environ.get(
        "FUSED_BENCH_NLAYERS", "1,2,4").split(",") if s.strip()]
    prior = {"fused_block_decode": flags.get_flag("fused_block_decode"),
             "fused_block_layers": flags.get_flag("fused_block_layers")}
    sweep = {}
    try:
        fused = run(True)
        unfused = run(False)
        # r17 cross-layer sweep: N=1 is the per-layer fused program
        # (== `fused` modulo timing noise but re-measured so every rung
        # shares one warm process), N>1 the grouped program. Each rung
        # keeps its best-of-k step time, and the k repeats are
        # round-robin-interleaved across rungs — host noise is temporally
        # correlated, so sequential per-rung blocks bias whole rungs
        repeats = int(os.environ.get("FUSED_BENCH_REPEATS", "3"))
        runs_by_n = {n: [] for n in sweep_ns}
        for _ in range(max(repeats, 1)):
            for n in sweep_ns:
                runs_by_n[n].append(run(True, nlayers=n))
        for n in sweep_ns:
            runs = runs_by_n[n]
            best = min(runs, key=lambda r: r["step_ms"])
            best["retraces_during_run"] = max(
                r["retraces_during_run"] for r in runs)
            sweep[n] = best
            emit({"phase": f"nlayer_{n}", "repeats": len(runs), **best})
    finally:
        flags.set_flags(prior)
    emit({"phase": "fused", **fused})
    emit({"phase": "unfused", **unfused})

    speedup = (round(unfused["step_ms"] / fused["step_ms"], 3)
               if fused["step_ms"] else None)
    per_layer_ms = sweep.get(1, fused)["step_ms"]
    grouped = {n: r for n, r in sweep.items() if n > 1}
    best_n = (min(grouped, key=lambda n: grouped[n]["step_ms"])
              if grouped else None)
    nlayer_ok = bool(
        grouped
        and grouped[best_n]["step_ms"] <= per_layer_ms
        and all(r["retraces_during_run"] == 0 for r in sweep.values()))
    # the banked row carries its own retrace/cache/latency evidence
    # (tools/telemetry_dump.py renders it back)
    from paddle_tpu import observability as obs
    telemetry = obs.registry().snapshot() if obs.enabled() else None
    emit({
        "metric": "fused_decode_step_ms",
        "telemetry": telemetry,
        "memory": obs.memory.section() if obs.enabled() else None,
        "value": fused["step_ms"],
        "unit": "ms_per_step",
        "vs_baseline": speedup,
        "fused_step_ms": fused["step_ms"],
        "unfused_step_ms": unfused["step_ms"],
        "fused_tokens_per_sec": fused["tokens_per_sec"],
        "unfused_tokens_per_sec": unfused["tokens_per_sec"],
        "decode_batch": batch,
        "decode_tokens": steps,
        "model": name,
        "fused_kind": fused["kind"],
        "nlayer_sweep": {str(n): r["step_ms"] for n, r in sweep.items()},
        "nlayer_kinds": {str(n): r["kind"] for n, r in sweep.items()},
        "nlayer_best": best_n,
        "nlayer_vs_per_layer": (round(per_layer_ms
                                      / grouped[best_n]["step_ms"], 3)
                                if grouped and grouped[best_n]["step_ms"]
                                else None),
        "nlayer_ok": nlayer_ok,
        "zero_retrace": fused["retraces_during_run"] == 0
        and unfused["retraces_during_run"] == 0,
        "bench_schema": BENCH_SCHEMA,
        "step": "fused_decode",
    })
    return 0


if __name__ == "__main__":
    sys.exit(main())
