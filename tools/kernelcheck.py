#!/usr/bin/env python
"""Pallas/TPU kernel-discipline lint gate (see
paddle_tpu/analysis/kernelcheck/).

Usage:
    python tools/kernelcheck.py paddle_tpu           # gate (exit 1 on new)
    python tools/kernelcheck.py paddle_tpu --json
    python tools/kernelcheck.py paddle_tpu --update-baseline
    python tools/kernelcheck.py --list-rules

Pure AST — the analysis package is loaded standalone (never through
``paddle_tpu/__init__``), so this runs in seconds with no jax import
and no device; safe as a pre-commit hook or bare CI step.  Unlike
tracecheck, the kernelcheck suite leans on its siblings (the shared
tracecheck parse + the jax-free ``tile_geometry`` module), so the
PARENT analysis package is what gets loaded, as ``ptanalysis``.

The checked-in baseline lives at tools/kernelcheck_baseline.json (kept
EMPTY — fix, don't baseline); the tier-1 test
(tests/test_kernelcheck.py) fails on any finding beyond it.

``python tools/analyze.py`` runs this suite AND tracecheck AND
meshcheck AND faultcheck over one shared parse — prefer it for the
full gate.
"""

import importlib
import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ANALYSIS_DIR = os.path.join(REPO, "paddle_tpu", "analysis")


def _load_standalone():
    """Import paddle_tpu.analysis WITHOUT triggering the framework's
    top-level __init__ (which pulls in jax), then hand back the
    kernelcheck CLI."""
    spec = importlib.util.spec_from_file_location(
        "ptanalysis", os.path.join(ANALYSIS_DIR, "__init__.py"),
        submodule_search_locations=[ANALYSIS_DIR])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["ptanalysis"] = mod
    spec.loader.exec_module(mod)
    return importlib.import_module("ptanalysis.kernelcheck.cli")


if __name__ == "__main__":
    sys.exit(_load_standalone().main())
