"""Generate API_COVERAGE.md: the paddle public API vs paddle_tpu.

The reference mount is empty (SURVEY.md provenance warning), so the
manifest below is a curated inventory of upstream PaddlePaddle's (~2.6)
public names per module — SURVEY.md §2.2's module inventory expanded to
name level. Each name is checked by attribute lookup on the installed
paddle_tpu. Run: python tools/api_coverage.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import toolenv  # noqa: E402

toolenv.force_cpu()

# ---------------------------------------------------------------- manifest
# module path (under paddle.*) -> public names (curated from the upstream
# API docs / SURVEY §2.2; "python/paddle/tensor/*" names surface at top level)
MANIFEST = {
    "": [  # top-level paddle.*
        # creation
        "to_tensor", "zeros", "ones", "full", "empty", "zeros_like",
        "ones_like", "full_like", "empty_like", "arange", "linspace",
        "logspace", "eye", "diag", "diagflat", "meshgrid", "tril", "triu",
        "rand", "randn", "randint", "randperm", "normal", "uniform",
        "bernoulli", "multinomial", "seed", "assign", "clone", "numel",
        "tolist", "complex", "real", "imag",
        # math
        "abs", "add", "subtract", "multiply", "divide", "floor_divide",
        "remainder", "mod", "pow", "sqrt", "rsqrt", "square", "exp",
        "expm1", "log", "log2", "log10", "log1p", "sin", "cos", "tan",
        "asin", "acos", "atan", "atan2", "sinh", "cosh", "tanh", "asinh",
        "acosh", "atanh", "ceil", "floor", "round", "trunc", "sign",
        "sgn", "clip", "maximum", "minimum", "fmax", "fmin", "max", "min",
        "amax", "amin", "sum", "nansum", "mean", "nanmean", "median",
        "nanmedian", "prod", "std", "var", "cumsum", "cumprod", "cummax",
        "cummin", "logcumsumexp", "logsumexp", "diff", "lerp", "rad2deg",
        "deg2rad", "gcd", "lcm", "erf", "erfinv", "lgamma", "digamma",
        "neg", "reciprocal", "frac", "trace", "kron", "inner", "outer",
        "heaviside", "nan_to_num", "angle", "conj", "hypot", "ldexp",
        "isfinite", "isinf", "isnan", "isclose", "allclose", "equal_all",
        # matmul / linalg at top level
        "matmul", "mm", "bmm", "dot", "t", "transpose", "dist", "cross",
        "cholesky", "addmm", "histogram", "histogramdd", "bincount",
        "mv", "count_nonzero",
        # logic / compare
        "equal", "not_equal", "greater_than", "greater_equal", "less_than",
        "less_equal", "logical_and", "logical_or", "logical_not",
        "logical_xor", "bitwise_and", "bitwise_or", "bitwise_not",
        "bitwise_xor", "is_tensor", "all", "any",
        # manipulation
        "reshape", "flatten", "squeeze", "unsqueeze", "concat", "stack",
        "split", "chunk", "tile", "expand", "expand_as", "broadcast_to",
        "broadcast_tensors", "flip", "rot90", "roll", "gather", "gather_nd",
        "scatter", "scatter_nd", "scatter_nd_add", "slice", "strided_slice",
        "index_select", "index_sample", "index_add", "index_put",
        "masked_select", "masked_fill", "take", "take_along_axis",
        "put_along_axis", "unbind", "unique", "unique_consecutive",
        "unfold", "repeat_interleave", "flatten_", "as_complex", "as_real",
        "moveaxis", "swapaxes", "tensordot", "einsum", "squeeze_",
        "unsqueeze_", "reshape_", "view", "view_as", "atleast_1d",
        "atleast_2d", "atleast_3d", "diagonal", "diag_embed",
        "tensor_split", "hsplit", "vsplit", "dsplit", "hstack", "vstack",
        "dstack", "column_stack", "row_stack", "pad",
        # search / sort
        "argmax", "argmin", "argsort", "sort", "topk", "kthvalue", "mode",
        "nonzero", "where", "searchsorted", "bucketize", "masked_scatter",
        # init / framework
        "CPUPlace", "CUDAPlace", "set_device", "get_device", "is_compiled_with_cuda",
        "no_grad", "grad", "enable_static", "disable_static", "in_dynamic_mode",
        "save", "load", "Tensor", "ParamAttr", "CPUPlace", "get_flags",
        "set_flags", "set_default_dtype", "get_default_dtype", "cast",
        "LazyGuard", "summary", "flops", "iinfo", "finfo",
        "set_grad_enabled", "is_grad_enabled", "enable_grad",
        # dtypes
        "float16", "float32", "float64", "bfloat16", "int8", "int16",
        "int32", "int64", "uint8", "bool",
    ],
    "nn": [
        "Layer", "LayerList", "Sequential", "ParameterList", "LayerDict",
        "Linear", "Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose",
        "Conv2DTranspose", "Conv3DTranspose", "Embedding", "Dropout",
        "Dropout2D", "Dropout3D", "AlphaDropout", "LayerNorm", "BatchNorm",
        "BatchNorm1D", "BatchNorm2D", "BatchNorm3D", "SyncBatchNorm",
        "GroupNorm", "InstanceNorm1D", "InstanceNorm2D", "InstanceNorm3D",
        "SpectralNorm", "LocalResponseNorm", "RMSNorm",
        "ReLU", "ReLU6", "LeakyReLU", "PReLU", "RReLU", "ELU", "CELU",
        "SELU", "GELU", "Hardshrink", "Hardsigmoid", "Hardswish",
        "Hardtanh", "Sigmoid", "LogSigmoid", "Softmax", "LogSoftmax",
        "Softplus", "Softshrink", "Softsign", "Swish", "SiLU", "Mish",
        "Tanh", "Tanhshrink", "ThresholdedReLU", "GLU", "Maxout",
        "MaxPool1D", "MaxPool2D", "MaxPool3D", "AvgPool1D", "AvgPool2D",
        "AvgPool3D", "AdaptiveAvgPool1D", "AdaptiveAvgPool2D",
        "AdaptiveAvgPool3D", "AdaptiveMaxPool1D", "AdaptiveMaxPool2D",
        "AdaptiveMaxPool3D", "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D",
        "ZeroPad2D", "Pad1D", "Pad2D", "Pad3D", "CosineSimilarity",
        "PairwiseDistance", "Upsample", "UpsamplingBilinear2D",
        "UpsamplingNearest2D", "PixelShuffle", "PixelUnshuffle",
        "ChannelShuffle", "Flatten", "Unfold", "Fold", "Identity",
        "RNN", "LSTM", "GRU", "SimpleRNN", "RNNCellBase", "LSTMCell",
        "GRUCell", "SimpleRNNCell", "BiRNN",
        "MultiHeadAttention", "Transformer", "TransformerEncoder",
        "TransformerEncoderLayer", "TransformerDecoder",
        "TransformerDecoderLayer",
        "CrossEntropyLoss", "MSELoss", "L1Loss", "NLLLoss", "BCELoss",
        "BCEWithLogitsLoss", "KLDivLoss", "SmoothL1Loss", "HuberLoss",
        "CosineEmbeddingLoss", "MarginRankingLoss", "TripletMarginLoss",
        "HingeEmbeddingLoss", "PoissonNLLLoss", "GaussianNLLLoss",
        "SoftMarginLoss", "MultiLabelSoftMarginLoss", "MultiMarginLoss",
        "CTCLoss", "RNNTLoss",
        "ClipGradByGlobalNorm", "ClipGradByNorm", "ClipGradByValue",
        "initializer", "functional", "utils",
    ],
    "nn.functional": [
        "linear", "conv1d", "conv2d", "conv3d", "conv1d_transpose",
        "conv2d_transpose", "conv3d_transpose", "embedding",
        "one_hot", "pad", "interpolate", "upsample", "grid_sample",
        "affine_grid", "pixel_shuffle", "pixel_unshuffle", "channel_shuffle",
        "relu", "relu6", "leaky_relu", "prelu", "rrelu", "elu", "celu",
        "selu", "gelu", "hardshrink", "hardsigmoid", "hardswish",
        "hardtanh", "sigmoid", "log_sigmoid", "softmax", "log_softmax",
        "softplus", "softshrink", "softsign", "swish", "silu", "mish",
        "tanhshrink", "thresholded_relu", "glu", "maxout", "gumbel_softmax",
        "max_pool1d", "max_pool2d", "max_pool3d", "avg_pool1d",
        "avg_pool2d", "avg_pool3d", "adaptive_avg_pool1d",
        "adaptive_avg_pool2d", "adaptive_avg_pool3d", "adaptive_max_pool1d",
        "adaptive_max_pool2d", "adaptive_max_pool3d",
        "dropout", "dropout2d", "dropout3d", "alpha_dropout",
        "normalize", "layer_norm", "batch_norm", "instance_norm",
        "group_norm", "local_response_norm", "rms_norm",
        "cross_entropy", "binary_cross_entropy",
        "binary_cross_entropy_with_logits", "mse_loss", "l1_loss",
        "nll_loss", "kl_div", "smooth_l1_loss", "margin_ranking_loss",
        "ctc_loss", "hinge_embedding_loss", "cosine_embedding_loss",
        "triplet_margin_loss", "poisson_nll_loss", "gaussian_nll_loss",
        "soft_margin_loss", "multi_label_soft_margin_loss",
        "multi_margin_loss", "huber_loss", "square_error_cost",
        "sigmoid_focal_loss", "dice_loss", "log_loss",
        "cosine_similarity", "pairwise_distance", "unfold", "fold",
        "scaled_dot_product_attention", "sequence_mask", "softmax_with_cross_entropy",
        "temporal_shift", "label_smooth", "zeropad2d",
    ],
    "linalg": [
        "matmul", "norm", "cond", "det", "slogdet", "inv", "pinv", "solve",
        "lstsq", "lu", "lu_unpack", "qr", "svd", "matrix_power",
        "matrix_rank", "eig", "eigh", "eigvals", "eigvalsh", "cholesky",
        "cholesky_solve", "triangular_solve", "multi_dot", "corrcoef",
        "cov", "householder_product", "svdvals", "matrix_exp",
    ],
    "fft": [
        "fft", "ifft", "fft2", "ifft2", "fftn", "ifftn", "rfft", "irfft",
        "rfft2", "irfft2", "rfftn", "irfftn", "hfft", "ihfft",
        "fftfreq", "rfftfreq", "fftshift", "ifftshift",
    ],
    "signal": ["stft", "istft"],
    "optimizer": [
        "Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adamax",
        "Adagrad", "Adadelta", "RMSProp", "Lamb", "LarsMomentum", "NAdam",
        "RAdam", "ASGD", "Rprop", "lr",
    ],
    "optimizer.lr": [
        "LRScheduler", "NoamDecay", "ExponentialDecay", "NaturalExpDecay",
        "InverseTimeDecay", "PolynomialDecay", "LinearWarmup",
        "PiecewiseDecay", "CosineAnnealingDecay", "StepDecay",
        "MultiStepDecay", "LambdaDecay", "ReduceOnPlateau",
        "OneCycleLR", "CyclicLR", "MultiplicativeDecay",
        "CosineAnnealingWarmRestarts",
    ],
    "io": [
        "Dataset", "IterableDataset", "TensorDataset", "ChainDataset",
        "ComposeDataset", "ConcatDataset", "Subset", "random_split",
        "DataLoader", "BatchSampler", "Sampler", "SequenceSampler",
        "RandomSampler", "WeightedRandomSampler", "DistributedBatchSampler",
        "get_worker_info",
    ],
    "distributed": [
        "init_parallel_env", "get_rank", "get_world_size", "spawn",
        "launch", "all_reduce", "all_gather", "all_gather_object",
        "all_to_all", "all_to_all_single", "broadcast", "reduce", "scatter",
        "gather", "reduce_scatter", "send", "recv", "isend", "irecv",
        "barrier", "batch_isend_irecv", "P2POp", "ReduceOp", "new_group",
        "get_group", "destroy_process_group", "is_initialized",
        "ProcessMesh", "shard_tensor", "dtensor_from_fn", "reshard",
        "shard_layer", "shard_optimizer", "Shard", "Replicate", "Partial",
        "DataParallel", "fleet", "Strategy", "to_static", "stream",
        "checkpoint", "save_state_dict", "load_state_dict",
    ],
    "distributed.fleet": [
        "init", "DistributedStrategy", "UserDefinedRoleMaker",
        "PaddleCloudRoleMaker", "worker_num", "worker_index",
        "distributed_model", "distributed_optimizer",
        "HybridCommunicateGroup", "get_hybrid_communicate_group",
    ],
    "amp": ["auto_cast", "GradScaler", "decorate", "debugging"],
    "jit": [
        "to_static", "not_to_static", "ignore_module", "save", "load",
        "TranslatedLayer",
    ],
    "static": ["InputSpec", "nn"],
    "static.nn": ["cond", "while_loop", "case", "switch_case"],
    "sparse": [
        "sparse_coo_tensor", "sparse_csr_tensor", "is_same_shape",
        "add", "subtract", "multiply", "divide", "matmul", "masked_matmul",
        "transpose", "sum", "nn",
    ],
    "distribution": [
        "Distribution", "Normal", "Uniform", "Bernoulli", "Categorical",
        "Beta", "Gamma", "Dirichlet", "Exponential", "Geometric",
        "Gumbel", "Laplace", "LogNormal", "Multinomial", "Poisson",
        "StudentT", "Cauchy", "Binomial", "ContinuousBernoulli",
        "ExponentialFamily", "Independent", "TransformedDistribution",
        "MultivariateNormal", "kl_divergence", "register_kl",
        "AbsTransform", "AffineTransform", "ChainTransform", "ExpTransform",
        "IndependentTransform", "PowerTransform", "ReshapeTransform",
        "SigmoidTransform", "SoftmaxTransform", "StackTransform",
        "StickBreakingTransform", "TanhTransform", "Transform",
    ],
    "vision": ["transforms", "datasets", "models", "ops"],
    "metric": ["Metric", "Accuracy", "Precision", "Recall", "Auc"],
    "incubate": ["nn"],
    "incubate.nn.functional": [
        "fused_multi_head_attention", "fused_feedforward",
        "fused_multi_transformer", "fused_linear", "fused_rms_norm",
        "fused_layer_norm", "fused_rotary_position_embedding",
        "fused_bias_dropout_residual_layer_norm", "fused_matmul_bias",
        "fused_linear_activation", "fused_linear_cross_entropy",
        "swiglu",
    ],
    "autograd": ["backward", "hessian", "jacobian", "PyLayer",
                 "PyLayerContext"],
    "profiler": ["Profiler", "ProfilerTarget", "ProfilerState",
                 "make_scheduler", "export_chrome_tracing"],
    "hapi": ["Model"],  # paddle.Model surfaces from hapi
}


def main():
    import paddle_tpu as paddle
    from api_manifest_extra import EXTRA

    for mod, names in EXTRA.items():
        MANIFEST.setdefault(mod, [])
        MANIFEST[mod] = sorted(set(MANIFEST[mod]) | set(names))

    rows = []
    missing_all = {}
    total_have = total_all = 0
    for mod, names in sorted(MANIFEST.items()):
        obj = paddle
        ok = True
        if mod == "Tensor":
            obj = paddle.Tensor      # method/property surface
        elif mod:
            for part in mod.split("."):
                obj = getattr(obj, part, None)
                if obj is None:
                    ok = False
                    break
        have = []
        missing = []
        for n in sorted(set(names)):
            if ok and getattr(obj, n, None) is not None:
                have.append(n)
            else:
                missing.append(n)
        label = "Tensor (methods)" if mod == "Tensor" else (mod or "paddle")
        rows.append((label, len(have), len(have) + len(missing)))
        if missing:
            missing_all["Tensor" if mod == "Tensor" else (mod or "paddle")] \
                = missing
        total_have += len(have)
        total_all += len(have) + len(missing)

    pct = 100.0 * total_have / total_all
    lines = [
        "# API coverage vs upstream paddle",
        "",
        f"**{total_have} / {total_all} names ({pct:.1f}%)** of the curated "
        "upstream public-API manifest resolve on `paddle_tpu` "
        "(`tools/api_coverage.py`; the reference mount is empty, so the "
        "manifest is curated from the upstream API docs per SURVEY.md "
        "§2.2 — regenerate after adding ops).",
        "",
        "| module | covered | total | % |",
        "|---|---|---|---|",
    ]
    for mod, have, tot in rows:
        lines.append(f"| paddle.{mod} | {have} | {tot} | "
                     f"{100.0 * have / tot:.0f}% |"
                     if mod != "paddle" else
                     f"| paddle | {have} | {tot} | "
                     f"{100.0 * have / tot:.0f}% |")
    lines += ["", "## Missing names", ""]
    for mod, names in sorted(missing_all.items()):
        lines.append(f"- **paddle.{mod}**: " + ", ".join(f"`{n}`"
                                                         for n in names))
    lines.append("")
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "API_COVERAGE.md")
    with open(out, "w") as f:
        f.write("\n".join(lines))
    print(f"wrote {out}: {total_have}/{total_all} = {pct:.1f}%")


if __name__ == "__main__":
    main()
