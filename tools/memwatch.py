"""memwatch CLI: what-if HBM planner + per-program memory regression gate.

Four subcommands over ``paddle_tpu/observability/memory.py`` (one
accounting code path with the live capture, the benches, and
``tools/memory_70b.py`` / ``tools/pipeline_memory.py``):

  **plan** — analytic serving-memory breakdown for a configuration that
  may be too big to compile locally, against a chip's HBM::

      python tools/memwatch.py plan --model llama2_7b --weight-dtype int8 \
          --kv-dtype int8 --page-budget 1024 --page-size 64 --rung 32 \
          --chunk 256 --max-seq 2048 --hbm-gb 16

  answers "does 7B int8 + page budget P + rung 32 + chunk 256 fit in
  16 GB?" with the transparent weights/pool/workspace/margin breakdown
  and the largest page budget that still fits.

  **bank** — run the tier-1-sized capture suite (tiny Llama fused +
  chunked serving, tiny GPT generic serving, tiny GPT train step) on
  this backend and bank every program's CompiledMemoryStats rows plus
  the estimator's predictions::

      python tools/memwatch.py bank --out MEMWATCH_r18.json

  **check** — re-run the same capture suite and flag any program whose
  temp/peak grew beyond tolerance vs the banked artifact (the memory
  analogue of the zero-retrace gate; exit code 1 on growth)::

      python tools/memwatch.py check --artifact MEMWATCH_r18.json

  **view** — render a banked artifact (or any bench row with a
  ``"memory"`` section) as a table.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import toolenv  # noqa: E402

# repo-root path setup is unconditional — backend forcing (below) is not
sys.path.insert(0, toolenv.repo_root())

SCHEMA = 1
GB = 1 << 30

_MODELS = ("llama_tiny", "llama2_7b", "llama2_70b", "gpt_tiny")


def _dims(name: str):
    from paddle_tpu.observability.memory import ModelDims

    if name == "gpt_tiny":
        from paddle_tpu.models import GPTConfig
        cfg = GPTConfig.tiny()
    else:
        from paddle_tpu.models import LlamaConfig
        ctor = {"llama_tiny": LlamaConfig.tiny,
                "llama2_7b": LlamaConfig.llama2_7b,
                "llama2_70b": LlamaConfig.llama2_70b}.get(name)
        if ctor is None:
            raise SystemExit(f"unknown --model {name!r} (have {_MODELS})")
        cfg = ctor()
    return ModelDims.of_config(cfg)


# ------------------------------------------------------------------ plan
def cmd_plan(args) -> int:
    from paddle_tpu.observability import memory as memwatch

    dims = _dims(args.model)
    kw = dict(page_size=args.page_size, max_batch=args.rung,
              max_seq_len=args.max_seq, chunk=args.chunk,
              weight_dtype=args.weight_dtype, kv_dtype=args.kv_dtype,
              host_tier_pages=args.host_tier_pages,
              tp=getattr(args, "tp", 1))
    if args.draft:
        # r16 speculative serving: the draft's weights + worst-case KV
        # pool are resident, the (1, gamma+1) verify chunk is workspace
        kw.update(draft_dims=_dims(args.draft),
                  spec_gamma=args.spec_gamma,
                  draft_weight_dtype=args.draft_weight_dtype
                  or args.weight_dtype)
    try:
        plan = memwatch.estimate_engine_memory(
            dims, page_budget=args.page_budget, **kw)
    except ValueError as e:
        # the r19 tensor-parallel refusal: a degree the engine itself
        # would reject (kv-head/head/MLP indivisibility) never gets an
        # HBM number — silently rounding would under-bill every shard
        print(f"# memwatch plan: {args.model} tp={kw['tp']}")
        print(f"  -> REFUSED: {e}")
        return 1
    hbm = int(args.hbm_gb * GB)
    verdict = memwatch.fits(plan, hbm)

    def fmt(b):
        return f"{b / GB:8.3f} GB" if b >= 1 << 20 else f"{b:8d} B "

    spec_note = (f" draft={args.draft} gamma={args.spec_gamma}"
                 if args.draft else "")
    tp_note = (f" tp={kw['tp']} [PER-SHARD bill: sharded weights + "
               f"kv-head-partitioned pool + per-shard workspaces]"
               if kw["tp"] > 1 else "")
    print(f"# memwatch plan: {args.model} weights={args.weight_dtype} "
          f"kv={args.kv_dtype} rung={args.rung} chunk={args.chunk} "
          f"pages={plan['config']['usable_pages']}x{args.page_size} "
          f"max_seq={args.max_seq} host_tier={args.host_tier_pages}"
          f"{spec_note}{tp_note}")
    for k, v in plan["breakdown"].items():
        print(f"  {k:32s} {fmt(v)}")
    print(f"  {'TOTAL (device HBM)':32s} {fmt(plan['total'])}")
    print(f"  {'HBM':32s} {fmt(hbm)}")
    print(f"  -> {'FITS' if verdict['fits'] else 'DOES NOT FIT'} "
          f"(headroom {verdict['headroom_bytes'] / GB:+.3f} GB)")
    # host-RAM KV tier: priced jointly, billed to host not HBM — the
    # serving ledger's kv_pool_bytes{state="spilled"} /
    # kv_host_tier_peak_pages gauges report the live tier against this
    ht = plan["host_tier"]
    if ht["pages"]:
        eff = ht["pages"] + plan["config"]["usable_pages"]
        print(f"  {'host KV tier (host RAM)':32s} {fmt(ht['bytes'])}  "
              f"[{ht['pages']} pages -> effective prefix working set "
              f"{eff} pages]")
        if args.host_ram_gb:
            host = int(args.host_ram_gb * GB)
            print(f"  {'host RAM':32s} {fmt(host)}  "
                  f"(tier headroom {(host - ht['bytes']) / GB:+.3f} GB)")
    # the planner's most actionable number: the largest page budget
    # that still fits this config (binary search over the analytic
    # model — each probe is arithmetic, not a compile)
    lo, hi = 0, 1 << 24
    while lo < hi:
        mid = (lo + hi + 1) // 2
        p = memwatch.estimate_engine_memory(dims, page_budget=mid, **kw)
        if p["total"] <= hbm:
            lo = mid
        else:
            hi = mid - 1
    toks = lo * args.page_size
    print(f"  max usable page budget at this HBM: {lo} pages "
          f"({toks} KV tokens, ~{toks // max(args.max_seq, 1)} full-length "
          f"sequences)")
    # ---- r17: N-layer fused decode kernel VMEM pricing. HBM fit says
    # nothing about whether the grouped kernel's working set (weight
    # double-buffers, per-layer page blocks, activation scratch) fits
    # per-core VMEM — an unfittable N is REFUSED here, before anyone
    # ships FLAGS_fused_block_layers=N to a chip.
    vplan = None
    if args.fused_layers > 1:
        io = 4 if args.weight_dtype == "float32" else 2
        vplan = memwatch.plan_fused_layers(
            dims, fused_layers=args.fused_layers, batch=args.rung,
            page_size=args.page_size, io_dtype_bytes=io,
            vmem_limit=int(args.vmem_mb * (1 << 20)))
        print(f"# fused decode VMEM: N={args.fused_layers} "
              f"rung={args.rung} io={io}B")
        for k, v in vplan["breakdown"].items():
            print(f"  {k:32s} {v:10d} B")
        print(f"  {'TOTAL (per-core VMEM)':32s} {vplan['total']:10d} B")
        print(f"  {'VMEM limit':32s} {vplan['vmem_limit']:10d} B")
        if not vplan["fits"]:
            print(f"  -> REFUSED: --fused-layers {args.fused_layers} "
                  f"does not fit {args.vmem_mb:g} MiB VMEM "
                  f"(over by {-vplan['headroom_bytes']} B); "
                  f"lower N or the decode rung")
        else:
            print(f"  -> VMEM FITS (headroom "
                  f"{vplan['headroom_bytes']} B)")
    if args.json:
        print(json.dumps({"plan": plan, "verdict": verdict,
                          "max_page_budget": lo,
                          **({"fused_vmem": vplan} if vplan else {})}))
    if vplan is not None and not vplan["fits"]:
        return 1
    return 0 if verdict["fits"] else 1


# ------------------------------------------------- capture suite (bank)
def capture_suite() -> dict:
    """Build + run the tier-1-sized programs with memwatch armed and
    return {rows, estimates, backend}: tiny-Llama serving (fused decode,
    monolithic prefill, chunked prefill), tiny-GPT serving (generic
    decode), and a tiny-GPT TrainStep. Deterministic byte sizes — the
    regression gate diffs these rows."""
    import numpy as np

    import jax

    import paddle_tpu as paddle
    from paddle_tpu import flags, observability as obs
    from paddle_tpu.generation.program_cache import (
        clear_decode_program_cache)
    from paddle_tpu.generation.serving import ServingEngine
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM, LlamaConfig,
                                   LlamaForCausalLM)
    from paddle_tpu.observability import memory as memwatch

    prior = flags.snapshot(("telemetry", "memwatch")).as_tuple()
    flags.set_flags({"telemetry": True, "memwatch": True})
    clear_decode_program_cache()
    memwatch.clear_program_table()
    rng = np.random.default_rng(13)
    estimates = []
    try:
        # --- tiny Llama: fused decode + monolithic prefill + chunk
        paddle.seed(13)
        lcfg = LlamaConfig.tiny()
        lmodel = LlamaForCausalLM(lcfg)
        eng = ServingEngine(lmodel, max_batch=2, page_size=8,
                            max_seq_len=48, prefill_chunk=8)
        for n in (6, 20):               # short (monolithic) + long (chunk)
            eng.submit(rng.integers(0, lcfg.vocab_size, (n,))
                       .astype(np.int32), 4)
        eng.run()
        estimates += _engine_estimates(eng, lcfg, chunk=8)
        # --- tiny Llama again, int8-quantized KV pool (r18): the fused
        # decode + prefill rows against a QuantizedPages pool — the gate
        # watches the quantized programs' sections (scale rows included)
        paddle.seed(13)
        qmodel = LlamaForCausalLM(lcfg)
        eng = ServingEngine(qmodel, max_batch=2, page_size=8,
                            max_seq_len=48, kv_dtype="int8")
        eng.submit(rng.integers(0, lcfg.vocab_size, (6,))
                   .astype(np.int32), 4)
        eng.run()
        estimates += _engine_estimates(eng, lcfg)
        # --- tiny Llama again, N-layer grouped decode (r17): banks the
        # decode_fused_nlayer rows so the gate watches the grouped
        # program's sections too
        paddle.seed(13)
        nprior = flags.snapshot(("fused_block_layers",)).as_tuple()
        flags.set_flags({"fused_block_layers": 2})
        try:
            nmodel = LlamaForCausalLM(lcfg)
            eng = ServingEngine(nmodel, max_batch=2, page_size=8,
                                max_seq_len=48)
            eng.submit(rng.integers(0, lcfg.vocab_size, (6,))
                       .astype(np.int32), 4)
            eng.run()
            estimates += _engine_estimates(eng, lcfg, fused_layers=2)
            # --- N-layer again with int4 weight tiles + int8 KV (r18):
            # the fully-quantized grouped program's rows
            paddle.seed(13)
            n4model = LlamaForCausalLM(lcfg)
            eng = ServingEngine(n4model, max_batch=2, page_size=8,
                                max_seq_len=48, kv_dtype="int8",
                                weight_dtype="int4")
            eng.submit(rng.integers(0, lcfg.vocab_size, (6,))
                       .astype(np.int32), 4)
            eng.run()
            estimates += _engine_estimates(eng, lcfg, fused_layers=2)
        finally:
            flags.set_flags(dict(nprior))
        # --- tiny GPT: generic decode path
        paddle.seed(13)
        gcfg = GPTConfig.tiny()
        gmodel = GPTForCausalLM(gcfg)
        eng = ServingEngine(gmodel, max_batch=2, page_size=8,
                            max_seq_len=48)
        eng.submit(rng.integers(0, gcfg.vocab_size, (6,))
                   .astype(np.int32), 4)
        eng.run()
        estimates += _engine_estimates(eng, gcfg)
        # --- tiny GPT train step
        _run_train_step(gcfg, gmodel, rng)
        rows = memwatch.program_table()
    finally:
        flags.set_flags(dict(prior))
        clear_decode_program_cache()
    return {"schema": SCHEMA, "bench": "memwatch",
            "backend": jax.default_backend(),
            "rows": rows, "estimates": estimates,
            "watermarks": memwatch.sample_device_memory(publish=False)}


def _engine_estimates(eng, cfg, chunk=None, fused_layers=1):
    """Estimator predictions for the engine's captured programs, with
    the compiled row alongside — the banked evidence that the analytic
    model tracks XLA's accounting."""
    import numpy as np

    from paddle_tpu.observability import memory as memwatch

    dims = memwatch.ModelDims.of_config(cfg)
    geom = memwatch.PoolGeometry.of_pool(eng.pool)
    pb = sum(memwatch.aval_bytes(v) for v in eng._params.values())
    pb += sum(memwatch.aval_bytes(v) for v in eng._buffers.values()
              if v is not None)
    out = []
    sig = eng._model_sig[:8]            # only THIS engine's programs
    # every DecodeKey.extra now carries the kv/weight dtype discriminant
    # (r18) — match it too, or a same-model engine pair (native + int8
    # pool) would cross-attribute each other's rows
    tag_kv = str(("kv", eng.kv_dtype))
    tag_wt = str(("wt", eng.weight_dtype))
    rows = {(r["kind"], r["bucket"], r["extra"]): r
            for r in memwatch.program_table()
            if r["model"] == sig and tag_kv in r["extra"]
            and tag_wt in r["extra"]}
    for (kind, bucket, extra), row in sorted(rows.items()):
        if kind == "decode_fused_nlayer":
            est = memwatch.estimate_decode_program(
                dims, geom, bucket, pb, fused_layers=fused_layers,
                int4_weights=eng.weight_dtype == "int4")
        elif kind.startswith("decode"):
            est = memwatch.estimate_decode_program(dims, geom, bucket, pb)
        elif kind == "prefill_chunk" and chunk:
            est = memwatch.estimate_prefill_program(dims, geom, chunk, pb,
                                                    chunked=True)
        elif kind == "prefill":
            # the captured prefill row is the LAST prompt length traced;
            # skip rows we cannot reconstruct the length for
            continue
        else:
            continue
        comp = row["temp"] + row["output"]
        pred = est["temp"] + est["output"]
        out.append({"model": sig, "kind": kind, "bucket": bucket,
                    "extra": extra, "estimate": est,
                    "compiled_temp_plus_output": comp,
                    "estimated_temp_plus_output": pred,
                    "rel_err": round(pred / comp - 1.0, 4) if comp else None})
    return out


def _run_train_step(cfg, model, rng):
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.hapi import TrainStep

    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters())

    def loss_fn(logits, y):
        import paddle_tpu.nn.functional as F
        return F.cross_entropy(
            logits.reshape([-1, logits.shape[-1]]), y.reshape([-1]))

    step = TrainStep(model, opt, loss_fn=loss_fn)
    ids = rng.integers(0, cfg.vocab_size, (2, 9))
    x = paddle.to_tensor(ids[:, :-1].astype(np.int32))
    y = paddle.to_tensor(ids[:, 1:].astype(np.int32))
    step(x, y)
    step.sync()
    step.sync_to_model()


def cmd_bank(args) -> int:
    doc = capture_suite()
    text = json.dumps(doc, indent=1, sort_keys=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"banked {len(doc['rows'])} program rows -> {args.out}")
    else:
        print(text)
    return 0


def cmd_check(args) -> int:
    from paddle_tpu.observability import memory as memwatch

    with open(args.artifact) as f:
        banked = json.load(f)
    doc = capture_suite()
    findings = memwatch.compare_program_rows(
        banked["rows"], doc["rows"], tolerance=args.tol)
    grew = [f for f in findings if f["verdict"] == "grew"]
    info = [f for f in findings if f["verdict"] != "grew"]
    missing = [f for f in info if f["verdict"] == "missing"]
    matched = len(banked["rows"]) - len(missing)
    for f in grew:
        # growth is None when the banked value was 0 (0 -> anything is
        # flagged, but has no finite ratio)
        why = (f"{f['growth']:+.1%} > {args.tol:.0%} tolerance"
               if f["growth"] is not None else "banked 0 -> nonzero")
        print(f"GREW  {f['model']}:{f['kind']}/b{f['bucket']}"
              f"{('/' + f['extra']) if f['extra'] else ''} {f['section']}: "
              f"{f['banked']} -> {f['current']} ({why})")
    for f in info:
        print(f"note  {f['model']}:{f['kind']}/b{f['bucket']}"
              f"{('/' + f['extra']) if f['extra'] else ''}: {f['verdict']}")
    if not matched:
        # a gate that compares nothing must not pass: zero overlap means
        # the capture suite is no longer measuring what was banked
        # (capture failures, renamed kinds/model sigs, broken backend)
        print(f"memwatch gate FAILED: no banked program matched a "
              f"captured row ({len(banked['rows'])} banked, "
              f"{len(doc['rows'])} captured) — re-bank or fix capture")
        return 1
    if not grew:
        print(f"memwatch gate OK: {matched} programs within "
              f"{args.tol:.0%} of {args.artifact}")
    return 1 if grew else 0


def cmd_view(args) -> int:
    from paddle_tpu.observability import memory as memwatch

    with open(args.artifact) as f:
        doc = json.load(f)
    rows = doc.get("rows") or doc.get("memory", {}).get("programs") or []
    if not rows:
        raise SystemExit("no program rows in artifact")
    print(memwatch.format_program_table(rows))
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("plan", help="what-if HBM fit planner")
    p.add_argument("--model", default="llama2_7b", choices=_MODELS)
    p.add_argument("--weight-dtype", default="bfloat16",
                   choices=("float32", "bfloat16", "int8", "int4"))
    p.add_argument("--kv-dtype", default="bfloat16",
                   choices=("bfloat16", "int8"))
    p.add_argument("--page-budget", type=int, default=None,
                   help="usable KV pages (default: worst-case formula)")
    p.add_argument("--page-size", type=int, default=64)
    p.add_argument("--rung", type=int, default=8,
                   help="decode batch bucket (ladder rung)")
    p.add_argument("--chunk", type=int, default=256)
    p.add_argument("--max-seq", type=int, default=2048)
    p.add_argument("--hbm-gb", type=float, default=16.0)
    p.add_argument("--host-tier-pages", type=int, default=0,
                   help="host-RAM KV tier pages "
                        "(FLAGS_serving_kv_host_tier_pages): priced "
                        "against host RAM, jointly with device HBM")
    p.add_argument("--host-ram-gb", type=float, default=0.0,
                   help="report host-tier headroom against this much "
                        "host RAM (0 = just report tier bytes)")
    p.add_argument("--draft", default=None, choices=_MODELS,
                   help="price speculative serving: this draft model's "
                        "weights + worst-case KV pool ride along, and "
                        "the (1, gamma+1) verify chunk joins the "
                        "workspace max")
    p.add_argument("--spec-gamma", type=int, default=4,
                   help="largest speculation rung to price the verify "
                        "chunk at (FLAGS_serving_spec_gamma)")
    p.add_argument("--draft-weight-dtype", default=None,
                   choices=("float32", "bfloat16", "int8", "int4"),
                   help="draft storage dtype (default: --weight-dtype)")
    p.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel degree: price ONE SHARD of "
                        "the r19 sharded decode engine (sharded "
                        "stacked weights, kv-head-partitioned pool "
                        "incl. the int8 scale band, per-shard "
                        "workspaces); refuses indivisible degrees")
    p.add_argument("--fused-layers", type=int, default=1,
                   help="price the N-layer fused decode kernel's VMEM "
                        "working set (FLAGS_fused_block_layers=N); an "
                        "N that does not fit --vmem-mb is refused "
                        "(exit 1)")
    p.add_argument("--vmem-mb", type=float, default=16.0,
                   help="per-core VMEM budget for --fused-layers")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_plan)

    p = sub.add_parser("bank", help="capture + bank program memory rows")
    p.add_argument("--out", default=None)
    p.set_defaults(fn=cmd_bank)

    p = sub.add_parser("check", help="regression gate vs banked artifact")
    p.add_argument("--artifact", default="MEMWATCH_r18.json")
    p.add_argument("--tol", type=float, default=0.10)
    p.set_defaults(fn=cmd_check)

    p = sub.add_parser("view", help="render a banked artifact")
    p.add_argument("artifact")
    p.set_defaults(fn=cmd_view)

    args = ap.parse_args()
    if os.environ.get("JAX_PLATFORMS") != "tpu":
        # bank/check build against the local backend; default cpu (set
        # JAX_PLATFORMS=tpu to bank on-chip rows). view/plan only need
        # the import, but force_cpu also scrubs the axon tunnel plugin
        # whose discovery can hang when the tunnel is down.
        toolenv.force_cpu()
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
