"""Synced-vs-async training-loop overhead A/B through Model.fit itself.

Measures what the async-by-default fit loop buys, with three arms all
driven through the trainer's real code path (hapi/model.py +
train_step.py), not a hand-rolled pipeline:

  eager   the pre-r07 ``Model.fit`` inner loop (``jit=False``: per-step
          ``train_batch`` + ``float(loss)`` — what a naive user got);
  synced  the jitted step with a per-step host pull
          (``metrics_every=1``, the TRAIN_AB_r05 "mfu_synced" arm);
  async   the dispatch-ahead loop (``metrics_every=k`` — stale-by-k
          pulls, hard sync only at epoch end; the new default).

On a shared-core CPU box the synced/async jitted arms are expected to be
CLOSE (the host thread blocked on a pull frees the core the "device"
compute needs — there is no idle chip to run ahead of); the pair is
banked anyway as the honest CPU datapoint, and the TPU window re-banks
the same A/B where the TRAIN_AB_r05 gap (MFU 0.4627 vs 0.2772) lives.
The eager arm is the loop the async default actually replaced.

Emits one JSON line per phase and a FINAL line in the standard bench.py
schema ({"metric", "value", "unit", "vs_baseline", ...}):

    value        = async steady-state step time, ms
    vs_baseline  = synced_step_ms / async_step_ms (the jitted A/B;
                   ~1.0 on CPU, the pipelining win on chip)

``--bank PATH`` additionally writes the chip_sprint ledger payload
({"step", "backend", "ts", "n_failed_checks", "results"}) so the
artifact parses with bench.artifact_state like every other BENCH_*.json.

Env knobs: LOOP_BENCH_STEPS (default 64), LOOP_BENCH_K (8),
LOOP_BENCH_REPEATS (3), BENCH_BATCH (8), BENCH_SEQ (32).
"""
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_BACKEND = "unknown"
BENCH_SCHEMA = 1
_LINES = []


def emit(d: dict) -> None:
    d.setdefault("backend", _BACKEND)
    _LINES.append(dict(d))
    print(json.dumps(d), flush=True)


def main() -> int:
    import numpy as np

    import jax

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.flags import is_tpu_backend
    from paddle_tpu.hapi import Model
    from paddle_tpu.io import Dataset
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    global _BACKEND
    _BACKEND = jax.default_backend()
    steps = int(os.environ.get("LOOP_BENCH_STEPS", "64"))
    k = int(os.environ.get("LOOP_BENCH_K", "8"))
    repeats = int(os.environ.get("LOOP_BENCH_REPEATS", "3"))
    batch = int(os.environ.get("BENCH_BATCH", "8"))
    seq = int(os.environ.get("BENCH_SEQ", "32"))
    on_tpu = is_tpu_backend()

    cfg = GPTConfig.tiny()
    emit({"phase": "init", "steps": steps, "metrics_every": k,
          "batch": batch, "seq": seq, "repeats": repeats,
          "n_params": cfg.num_params()})

    class LMDataset(Dataset):
        def __init__(self):
            rng = np.random.default_rng(0)
            self.data = rng.integers(0, cfg.vocab_size,
                                     (steps * batch, seq + 1)).astype(np.int32)

        def __len__(self):
            return len(self.data)

        def __getitem__(self, i):
            return self.data[i, :-1], self.data[i, 1:]

    ds = LMDataset()

    def ce(logits, y):
        return F.cross_entropy(logits.reshape([-1, logits.shape[-1]]),
                               y.reshape([-1]))

    def build():
        paddle.seed(0)
        net = GPTForCausalLM(cfg)
        if on_tpu:
            net.to(dtype="bfloat16")
        model = Model(net)
        model.prepare(
            paddle.optimizer.AdamW(1e-4, parameters=net.parameters(),
                                   multi_precision=on_tpu),
            loss=ce)
        return model

    def fit_once(metrics_every):
        model = build()
        t0 = time.perf_counter()
        model.fit(ds, batch_size=batch, epochs=1, metrics_every=1,
                  num_iters=2, verbose=0)           # compile (untimed)
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        model.fit(ds, batch_size=batch, epochs=1,
                  metrics_every=metrics_every, verbose=0)
        wall = time.perf_counter() - t0
        ts = model._train_step
        return {"wall_s": wall, "compile_s": compile_s,
                "syncs": ts.sync_count, "traces": ts.trace_count,
                "throttles": ts.throttle_count}

    def arm(metrics_every, name):
        runs = [fit_once(metrics_every) for _ in range(repeats)]
        best = min(runs, key=lambda r: r["wall_s"])
        rec = {"phase": name, "metrics_every": metrics_every,
               "step_ms": round(best["wall_s"] / steps * 1000, 3),
               "wall_s": round(best["wall_s"], 3),
               "all_wall_s": [round(r["wall_s"], 3) for r in runs],
               "syncs_per_epoch": best["syncs"],
               "traces": best["traces"],
               "throttles": best["throttles"],
               "ok": best["traces"] == 1 and best["throttles"] == 0}
        emit(rec)
        return rec

    # alternating arms would halve cache-thermal bias, but each fit is
    # already best-of-N with a fresh Model; interleave at the run level
    synced = arm(1, "synced")
    is_async = arm(k, "async")

    # the pre-r07 loop: eager per-step train_batch + float(loss). Scaled
    # down (it is ~30x slower on CPU); step_ms is the comparable figure.
    eager_steps = min(steps, int(os.environ.get("LOOP_BENCH_EAGER_STEPS",
                                                "16")))
    model = build()
    model.fit(ds, batch_size=batch, epochs=1, jit=False, num_iters=2,
              verbose=0)                            # warm eager caches
    t0 = time.perf_counter()
    model.fit(ds, batch_size=batch, epochs=1, jit=False,
              num_iters=eager_steps, verbose=0)
    eager_wall = time.perf_counter() - t0
    eager = {"phase": "eager", "steps": eager_steps,
             "step_ms": round(eager_wall / eager_steps * 1000, 3),
             "wall_s": round(eager_wall, 3)}
    emit(eager)

    speedup = (round(synced["step_ms"] / is_async["step_ms"], 3)
               if is_async["step_ms"] else None)
    # the banked row carries its own sync/throttle/retrace evidence
    # (tools/telemetry_dump.py renders it back)
    from paddle_tpu import observability as obs
    telemetry = obs.registry().snapshot() if obs.enabled() else None
    emit({
        "metric": "fit_async_step_ms",
        "telemetry": telemetry,
        "value": is_async["step_ms"],
        "unit": "ms_per_step",
        "vs_baseline": speedup,
        "synced_step_ms": synced["step_ms"],
        "async_step_ms": is_async["step_ms"],
        "eager_step_ms": eager["step_ms"],
        "speedup_vs_eager_loop": round(
            eager["step_ms"] / is_async["step_ms"], 2),
        "metrics_every": k,
        "fit_steps": steps,
        "async_syncs_per_epoch": is_async["syncs_per_epoch"],
        "synced_syncs_per_epoch": synced["syncs_per_epoch"],
        "zero_retrace": is_async["traces"] == 1 and synced["traces"] == 1,
        "n_chips": jax.device_count(),
        "bench_schema": BENCH_SCHEMA,
        "step": "loop_overhead",
    })

    if "--bank" in sys.argv:
        path = sys.argv[sys.argv.index("--bank") + 1]
        bad = [l for l in _LINES if l.get("ok") is False]
        payload = {"step": "loop_overhead", "backend": _BACKEND,
                   "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
                   "n_failed_checks": len(bad), "results": _LINES}
        with open(path, "w") as f:
            json.dump(payload, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
