"""Seeded Poisson multi-tenant load generator for the serving engine
and (``--fleet``) the multi-replica fleet router.

The acceptance bench for the r12 production continuous-batching loop:
a deterministic (seeded) open-loop Poisson request stream from several
tenants — a chat tenant with short shared-prefix prompts, a long-prompt
tenant (the decode-stall antagonist), and an SLO tenant submitting with
deadlines — is paced in real time against a ServingEngine, twice:

  chunked      chunked prefill + the bucket ladder (the r12 loop)
  monolithic   whole-prompt prefill, fixed top-rung bucket (pre-r12)

Each arm runs a WARMUP pass first (same prompt-length set, every ladder
rung dispatched) so the measured pass exercises steady state; metrics
come from the r09 telemetry snapshot DELTA across the measured pass:

  - sustained throughput (generated tokens / wall)
  - p50/p99 TTFT and inter-token latency (histogram bucket deltas)
  - ZERO program-cache traces at steady state (the retrace ledger)
  - max decode stall (engine probe): with chunking the worst stall a
    long-prompt arrival imposes on decoding requests is ~one chunk;
    monolithic pays the whole prompt — the artifact asserts
    chunked_max < monolithic_max

plus a cross-arm greedy BIT-IDENTITY check (same schedule, same rids,
same tokens). ``--out SERVING_LOAD_r12.json`` banks the ledger;
``--quick`` is the deterministic tier-1 slice driven by
tests/test_serving_load.py (marker ``serving_load``).

``--fleet`` (r14) runs the FLEET acceptance bench instead — three
sections over ``paddle_tpu/generation/fleet.py``:

  routing     N replicas, per-org shared-prefix tenants, Poisson
              arrivals, prefix-AFFINITY vs ROUND-ROBIN arms: affinity
              concentrates each org's prefix on one replica (shared
              admissions skip prefill) while round-robin smears it
              across all N and thrashes eviction — TTFT p99 must be
              lower under affinity, outputs bit-identical, with
              per-replica telemetry deltas banked.
  preemption  2 replicas saturated by no-deadline long generations
              while tight-deadline arrivals land: FLAGS_serving_preempt
              on vs off. The on-arm must hold tight-tenant TTFT p99
              under the off-arm's while every preempted victim still
              finishes bit-identically (replay-from-host-state IS the
              preemption mechanism).
  tiering     one replica whose device page budget is SMALLER than the
              org-prefix working set, host tier armed, vs a big-pool
              no-tier reference: spills + restores must occur, the
              registered working set must exceed the device budget,
              and every output must match the reference bit-for-bit.

``--out FLEET_LOAD_r14.json`` banks that ledger; the quick slice is
driven by tests/test_fleet.py (marker ``fleet``).

``--spec`` (r16) runs the SPECULATIVE-DECODING acceptance bench — two
sections over the ServingEngine's draft/verify mode:

  throughput  the batch-1 A/B the feature exists for: one request,
              plain decode vs speculative rounds, REPEATS measured
              passes per arm after a warmup pass that compiles every
              γ-rung program. Bars: ≥1.8x tokens/s (min over passes,
              both arms), greedy outputs bit-identical, ZERO
              steady-state retraces across all measured passes.
  occupancy   the γ+1 slot bill made visible: 1/2/4/8 concurrent
              requests against the same engine geometry, recording the
              largest γ any round ran at while ALL rows were live —
              the ladder must fall monotonically (8, 4, 2, then 0 =
              speculation priced out entirely at a full batch), with
              every row's outputs bit-identical to the plain engine.

The draft-agreement rig mirrors the production shape (a truncated /
distilled draft of the serving target): the 4-layer target's upper
layers are damped to near-identity residuals and the 1-layer draft
SHARES the target's embedding, layer-0, final-norm and head weights —
high agreement with real rejections, at a quarter of the layer cost.
``--out SPEC_DECODE_r16.json`` banks the ledger.

``--kv-dtype int8`` (r18) runs the QUANTIZED-KV acceptance bench — a
native-vs-int8 pool A/B at FIXED pool memory: the native arm's pool
bytes re-spent on int8 pages (payload + per-token f32 scales) must buy
~2x the usable page budget, measured from the pool LEDGER rather than
the planner, the page-pressure queueing regime must recede (smaller
queue-depth integral over the drain), int8 re-runs are bit-identical
(deterministic amax quantization), the analytic ``memwatch plan`` pool
term agrees with the ledger within 10%, and the retrace ledger stays
at zero. ``--out KV_QUANT_r18.json`` banks the ledger.
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SCHEMA = 1

# tenant mix: (name, rate req/s, prompt lengths cycled, shared-prefix
# tokens, max_new, deadline seconds or None)
TENANTS = (
    ("chat", 24.0, (12, 24), 8, 12, None),
    ("long", 4.0, (320,), 0, 8, None),
    ("slo", 12.0, (16,), 0, 8, 30.0),
)
QUICK_TENANTS = (
    ("chat", 20.0, (12,), 8, 6, None),
    # long prompts must be long enough that prefill cost is token-work,
    # not dispatch overhead, or the stall comparison loses its margin
    # at tiny-model scale
    ("long", 6.0, (320,), 0, 6, None),
    ("slo", 10.0, (16,), 0, 4, 30.0),
)


def make_arrivals(tenants, per_tenant, vocab, seed):
    """The deterministic request schedule: per-tenant exponential
    inter-arrival gaps and prompt bodies from a private seeded stream
    (tenant prompts share a fixed prefix to exercise the prefix cache),
    merged by arrival time."""
    import numpy as np

    arrivals = []
    for ti, (name, rate, lens, shared, max_new, deadline) in \
            enumerate(tenants):
        rng = np.random.default_rng((seed, ti))
        prefix = rng.integers(0, vocab, (shared,)).astype(np.int32)
        t = 0.0
        for i in range(per_tenant):
            t += float(rng.exponential(1.0 / rate))
            ln = int(lens[i % len(lens)])
            body = rng.integers(0, vocab, (ln - shared,)).astype(np.int32)
            prompt = np.concatenate([prefix, body]).astype(np.int32)
            arrivals.append(dict(t=t, tenant=name, prompt=prompt,
                                 max_new=int(max_new), deadline=deadline))
    arrivals.sort(key=lambda a: (a["t"], a["tenant"]))
    return arrivals


def make_engine(model, arm, cfg):
    from paddle_tpu.generation.serving import ServingEngine

    chunked = arm == "chunked"
    return ServingEngine(
        model, max_batch=cfg["max_batch"], page_size=cfg["page_size"],
        max_seq_len=cfg["max_seq_len"], prefix_cache=True,
        bucket_ladder=(cfg["ladder"] if chunked
                       else (cfg["max_batch"],)),
        prefill_chunk=(cfg["chunk"] if chunked else 0))


def warmup_arm(model, arm, cfg, lens):
    """Compile every program the measured pass can touch: one prefill
    per distinct prompt length (or the chunk program for long ones),
    and one decode dispatch at EVERY ladder rung — a rung first visited
    mid-measurement would read as a steady-state retrace."""
    import numpy as np

    rng = np.random.default_rng(0)
    eng = make_engine(model, arm, cfg)
    for ln in sorted(set(lens)):
        eng.submit(rng.integers(0, cfg["vocab"], (ln,)).astype(np.int32),
                   4)
        eng.run(max_wall=300.0)
    for rung in eng.ladder:
        for _ in range(rung):
            eng.submit(rng.integers(0, cfg["vocab"], (8,))
                       .astype(np.int32), 4)
        eng.run(max_wall=300.0)


def trace_total(snap):
    fam = snap["metrics"].get("program_cache_traces")
    if fam is None:
        return 0.0
    return sum(s["value"] for s in fam["series"])


def hist_delta(before, after, name):
    """Measured-pass histogram view: bucket-wise delta of the two
    cumulative snapshots (min/max dropped — unknown for the window)."""
    fa = after["metrics"].get(name)
    if fa is None or not fa["series"]:
        return None
    sa = fa["series"][0]
    fb = before["metrics"].get(name)
    if fb is None or not fb["series"]:
        return dict(sa)
    sb = fb["series"][0]
    return {"labels": {}, "count": sa["count"] - sb["count"],
            "sum": sa["sum"] - sb["sum"], "buckets": sa["buckets"],
            "counts": [a - b for a, b in zip(sa["counts"], sb["counts"])],
            "min": None, "max": None}


def quantiles(before, after, name, qs=(0.5, 0.99)):
    from paddle_tpu.observability import series_quantile

    entry = hist_delta(before, after, name)
    if entry is None or not entry["count"]:
        return {f"p{int(q * 100)}": None for q in qs}
    return {f"p{int(q * 100)}": round(series_quantile(entry, q), 6)
            for q in qs}


# virtual steps per second: the arrival clock ticks once per scheduler
# round rather than per wall second, so WHICH step each request lands
# on — and therefore whether a long-prompt arrival overlaps live
# decodes — is a pure function of the seed, not of machine load.
# Latencies are still measured in real wall time.
STEPS_PER_SEC = 250


REPEATS = 3     # measured passes per arm: the banked max stall is the
# MIN over passes of each pass's max — the schedule is deterministic,
# so the structural worst stall recurs every pass while a one-off OS/GC
# spike does not (a single pass's max is spike-polluted on shared CPU)


def run_arm(model, arm, cfg, arrivals):
    """The measured passes for one arm: warmed programs, deterministic
    step-indexed pacing, streaming callbacks collecting every token,
    telemetry snapshot delta spanning all passes (so the zero-retrace
    bar covers every pass)."""
    import paddle_tpu.observability as obs

    warmup_arm(model, arm, cfg,
               [len(a["prompt"]) for a in arrivals])
    due = [int(a["t"] * STEPS_PER_SEC) for a in arrivals]

    def one_pass():
        eng = make_engine(model, arm, cfg)
        streamed = {}

        def on_token(rid, tok, done):
            if not done:
                streamed.setdefault(rid, []).append(tok)

        rids = []
        i = 0
        tick = 0
        t0 = time.perf_counter()
        while i < len(arrivals) or eng.has_work():
            while i < len(arrivals) and due[i] <= tick:
                a = arrivals[i]
                rids.append(eng.submit(a["prompt"], a["max_new"],
                                       deadline=a["deadline"],
                                       on_token=on_token))
                i += 1
            tick += 1
            if eng.has_work():
                eng.run_step()
        wall = time.perf_counter() - t0
        return eng, rids, streamed, wall

    before = obs.snapshot()
    walls, stalls = [], []
    for _ in range(REPEATS):
        eng, rids, streamed, wall = one_pass()
        walls.append(wall)
        stalls.append(round(eng.max_decode_stall, 6))
    after = obs.snapshot()

    out = eng.results()
    statuses = [eng.status(r) for r in rids]
    tokens_total = sum(len(out.get(r, [])) for r in rids)
    wall = walls[-1]
    metrics = {
        "requests": len(rids),
        "passes": REPEATS,
        "statuses": {s: statuses.count(s) for s in set(statuses)},
        "all_ok": all(s == "OK" for s in statuses),
        "tokens_total": tokens_total,
        "wall_s": round(wall, 4),
        "tokens_per_s": round(tokens_total / wall, 2) if wall else None,
        "ttft_s": quantiles(before, after, "serving_ttft_seconds"),
        "inter_token_s": quantiles(before, after,
                                   "serving_inter_token_seconds"),
        "prefill_chunk_s": quantiles(before, after,
                                     "serving_prefill_chunk_seconds"),
        "decode_stall_s": quantiles(before, after,
                                    "serving_decode_stall_seconds"),
        "max_decode_stall_s": min(stalls),
        "max_decode_stall_per_pass_s": stalls,
        "steady_retraces": trace_total(after) - trace_total(before),
        "bucket_migrations": eng.bucket_migrations,
        "chunk_dispatches": eng.chunk_dispatches,
        "streamed_matches_results": all(
            streamed.get(r, []) == out.get(r, []) for r in rids),
    }
    return metrics, {r: out.get(r, []) for r in rids}


def bench(per_tenant, seed, quick=False):
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    tenants = QUICK_TENANTS if quick else TENANTS
    cfg = (dict(vocab=256, max_batch=8, page_size=8,
                max_seq_len=384, ladder=(2, 4, 8), chunk=16)
           if quick else
           dict(vocab=256, max_batch=8, page_size=8,
                max_seq_len=512, ladder=(2, 4, 8), chunk=32))
    paddle.seed(1234)
    mcfg = GPTConfig.tiny()
    # the long tenant's prompts need position room beyond tiny's 128
    mcfg.max_position_embeddings = cfg["max_seq_len"]
    model = GPTForCausalLM(mcfg)
    arrivals = make_arrivals(tenants, per_tenant, cfg["vocab"], seed)

    arms = {}
    outputs = {}
    for arm in ("chunked", "monolithic"):
        arms[arm], outputs[arm] = run_arm(model, arm, cfg, arrivals)

    parity = outputs["chunked"] == outputs["monolithic"]
    c_stall = arms["chunked"]["max_decode_stall_s"]
    m_stall = arms["monolithic"]["max_decode_stall_s"]
    stall = {
        "chunked_max_s": c_stall,
        "monolithic_max_s": m_stall,
        # the acceptance bar: the worst stall any decoding request saw
        # shrinks from a whole-prompt prefill to ~one chunk. Both
        # maxima are min-over-passes (the structural stall recurs every
        # pass; a one-off OS/GC spike does not), the long tenant's
        # prompts are 10-20x the chunk so the margin survives ordinary
        # shared-CPU noise, and overlap between a long arrival and live
        # decodes is deterministic (step-indexed pacing), not a race
        # against machine load.
        "ratio": round(c_stall / m_stall, 4) if m_stall else None,
        "bounded_by_chunk": bool(m_stall and c_stall < m_stall),
    }
    ok = (parity
          and stall["bounded_by_chunk"]
          and all(a["all_ok"] for a in arms.values())
          and all(a["steady_retraces"] == 0 for a in arms.values())
          and all(a["streamed_matches_results"] for a in arms.values()))
    import paddle_tpu.observability as obs
    return {
        "schema": SCHEMA, "bench": "serving_load",
        "backend": jax.default_backend(), "seed": seed,
        "config": {**{k: v for k, v in cfg.items()},
                   "ladder": list(cfg["ladder"]),
                   "tenants": [list(t[:2]) + [list(t[2])] + list(t[3:])
                               for t in tenants],
                   "requests_per_tenant": per_tenant,
                   "quick": bool(quick)},
        "arms": arms,
        "parity_bit_identical": parity,
        "stall": stall,
        "ok": bool(ok),
        "telemetry": obs.snapshot(),
        # memwatch: the chunk/ladder programs' compiled-memory rows ride
        # the banked artifact (telemetry_dump --memory renders them)
        "memory": obs.memory.section() if obs.enabled() else None,
    }


# ===================================================== fleet bench (r14)
FLEET_SCHEMA = 1


def replica_counter_deltas(before, after, names):
    """Per-replica counter/histogram-count deltas: the per-replica
    telemetry view the r14 `replica` label makes possible."""
    out = {}
    for name in names:
        fa = after["metrics"].get(name)
        if fa is None:
            continue
        prev = {}
        fb = before["metrics"].get(name)
        if fb is not None:
            prev = {tuple(sorted(s["labels"].items())): s
                    for s in fb["series"]}
        for s in fa["series"]:
            rep = s["labels"].get("replica", "")
            b = prev.get(tuple(sorted(s["labels"].items())))
            if "value" in s:
                d = s["value"] - (b["value"] if b else 0.0)
            else:
                d = s["count"] - (b["count"] if b else 0)
            if d:
                out.setdefault(rep, {})[name] = round(d, 6)
    return out


_FLEET_REPLICA_FAMILIES = (
    "serving_requests_submitted", "serving_prefills",
    "serving_shared_admissions", "serving_ttft_seconds",
    "prefix_cache_hits", "prefix_cache_misses",
    "prefix_cache_hit_pages", "prefix_cache_evicted_pages",
    "prefix_cache_spilled_pages", "prefix_cache_restored_pages",
    "serving_preemptions", "serving_requests_timeout",
    "fleet_requests_routed")


def _fleet_model(cfg):
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(1234)
    mcfg = GPTConfig.tiny()
    mcfg.max_position_embeddings = cfg["max_seq_len"]
    return GPTForCausalLM(mcfg)


def make_org_arrivals(n_orgs, per_org, prefix_len, body_len, vocab, seed,
                      max_new, deadline=None, rate=20.0):
    """Per-org shared-prefix Poisson arrivals: each org's prompts open
    with the org's own ``prefix_len``-token system prompt."""
    import numpy as np

    arrivals = []
    for oi in range(n_orgs):
        rng = np.random.default_rng((seed, oi))
        prefix = rng.integers(0, vocab, (prefix_len,)).astype(np.int32)
        t = 0.0
        for _ in range(per_org):
            t += float(rng.exponential(1.0 / rate))
            body = rng.integers(0, vocab, (body_len,)).astype(np.int32)
            arrivals.append(dict(
                t=t, tenant=f"org{oi}",
                prompt=np.concatenate([prefix, body]),
                max_new=int(max_new), deadline=deadline))
    arrivals.sort(key=lambda a: (a["t"], a["tenant"]))
    return arrivals


def _drive_fleet(fleet, arrivals, max_wall=300.0):
    """Deterministic step-indexed pacing (the r12 discipline): WHICH
    router round each arrival lands on is a pure function of the
    schedule, not machine load. TTFT comes from HOST stamps (submit
    wall -> first streamed token wall) so the A/B compares exact
    values, not histogram-bucket interpolations."""
    import time as _time

    due = [int(a["t"] * STEPS_PER_SEC) for a in arrivals]
    submit_t, ttft = {}, {}

    def cb(rid, tok, done):
        if not done and rid not in ttft:
            ttft[rid] = _time.perf_counter() - submit_t[rid]

    rids, i, tick = [], 0, 0
    t0 = _time.perf_counter()
    while i < len(arrivals) or fleet.has_work():
        if _time.perf_counter() - t0 > max_wall:
            break
        while i < len(arrivals) and due[i] <= tick:
            a = arrivals[i]
            ts = _time.perf_counter()
            rid = fleet.submit(a["prompt"], a["max_new"],
                               deadline=a["deadline"], on_token=cb)
            submit_t[rid] = ts
            rids.append(rid)
            i += 1
        tick += 1
        if fleet.has_work():
            fleet.run_step()
    st = fleet.statuses()               # BEFORE the drain frees them
    out = fleet.take_results()
    return rids, out, {r: st.get(r, "PENDING") for r in rids}, ttft


def fleet_routing_section(cfg, seed):
    """Affinity vs round-robin A/B over identical arrivals. Pass 0 is
    the WARMUP (programs compile, caches fill — each org's first
    request is a cold miss under either policy); the measured passes
    run the same schedule against the warm fleet, where affinity keeps
    every org on its cache-resident replica while round-robin smears
    the orgs across all replicas and thrashes eviction."""
    import numpy as np

    import paddle_tpu.observability as obs
    from paddle_tpu.generation.fleet import FleetRouter

    model = _fleet_model(cfg)
    arrivals = make_org_arrivals(
        cfg["orgs"], cfg["per_org"], cfg["prefix"], cfg["body"],
        cfg["vocab"], seed, cfg["max_new"])

    arms, outputs = {}, {}
    for policy in ("prefix_affinity", "round_robin"):
        fleet = FleetRouter(
            model, replicas=cfg["replicas"], policy=policy,
            max_batch=cfg["max_batch"], page_size=cfg["page_size"],
            max_seq_len=cfg["max_seq_len"], num_pages=cfg["num_pages"])
        _drive_fleet(fleet, arrivals)           # warmup pass
        p99s, passes = [], []
        before = obs.snapshot()
        for _ in range(REPEATS):
            rids, out, st, ttft = _drive_fleet(fleet, arrivals)
            vals = [ttft[r] for r in rids if r in ttft]
            q = {"p50": round(float(np.quantile(vals, 0.5)), 6),
                 "p99": round(float(np.quantile(vals, 0.99)), 6)}
            p99s.append(q["p99"])
            passes.append(q)
        after = obs.snapshot()
        arms[policy] = {
            "requests": len(rids),
            "all_ok": all(s == "OK" for s in st.values()),
            # min over passes: the structural gap (prefill skipped vs
            # re-run) recurs every pass, a one-off OS spike does not
            "ttft_p99_s": min(p99s),
            "ttft_per_pass": passes,
            "per_replica": replica_counter_deltas(
                before, after, _FLEET_REPLICA_FAMILIES),
            "placements": {why: sum(1 for _, _, w in fleet.placements
                                    if w == why)
                           for why in ("affinity", "balance",
                                       "round_robin", "pinned")},
        }
        outputs[policy] = {r: out.get(r, []) for r in rids}
    parity = outputs["prefix_affinity"] == outputs["round_robin"]
    aff, rr = arms["prefix_affinity"], arms["round_robin"]
    ok = (parity and aff["all_ok"] and rr["all_ok"]
          and aff["ttft_p99_s"] < rr["ttft_p99_s"]
          and aff["placements"]["affinity"] > 0)
    return {"arms": arms, "parity_bit_identical": parity,
            "ttft_p99_ratio": round(
                aff["ttft_p99_s"] / rr["ttft_p99_s"], 4)
            if rr["ttft_p99_s"] else None,
            "ok": bool(ok)}


def fleet_preemption_section(cfg, seed):
    """Tight-deadline p99 under overload: FLAGS_serving_preempt A/B."""
    import numpy as np

    import paddle_tpu.observability as obs
    from paddle_tpu import flags
    from paddle_tpu.generation.fleet import FleetRouter

    model = _fleet_model(cfg)
    rng = np.random.default_rng((seed, 99))
    batch_prompts = [rng.integers(0, cfg["vocab"], (12,)).astype(np.int32)
                     for _ in range(cfg["replicas"] * cfg["max_batch"])]
    slo_prompts = [rng.integers(0, cfg["vocab"], (10,)).astype(np.int32)
                   for _ in range(cfg["slo_requests"])]

    # one warmup fleet compiles everything both arms touch: chunked
    # prefill (all prompts AND replay feeds exceed the chunk, so no
    # prompt length ever forces a fresh compile mid-measurement) plus
    # the decode rung
    warm = FleetRouter(model, replicas=1, max_batch=cfg["max_batch"],
                       page_size=cfg["page_size"],
                       max_seq_len=cfg["max_seq_len"],
                       prefill_chunk=cfg["page_size"])
    warm.submit(batch_prompts[0], 2)
    warm.submit(slo_prompts[0], 2)
    warm.run(max_wall=120.0)

    def run_arm(preempt_on):
        import time as _time

        prev = {k: flags.get_flag(k) for k in
                ("serving_preempt", "serving_preempt_horizon")}
        flags.set_flags({"serving_preempt": preempt_on,
                         "serving_preempt_horizon": 30.0})
        try:
            before = obs.snapshot()
            fleet = FleetRouter(
                model, replicas=cfg["replicas"],
                max_batch=cfg["max_batch"], page_size=cfg["page_size"],
                max_seq_len=cfg["max_seq_len"],
                prefill_chunk=cfg["page_size"])
            # saturate every slot with no-deadline long generations
            brids = [fleet.submit(p, cfg["batch_tokens"],
                                  replica=i % cfg["replicas"])
                     for i, p in enumerate(batch_prompts)]
            guard = 0
            while any(e._slots.count(None) for e in fleet.engines) \
                    and guard < 200:
                fleet.run_step()        # until every slot is decoding
                guard += 1
            # tight-deadline arrivals land mid-overload; TTFT from
            # host stamps, slo tenant only
            submit_t, ttft = {}, {}

            def cb(rid, tok, done):
                if not done and rid not in ttft:
                    ttft[rid] = _time.perf_counter() - submit_t[rid]

            srids = []
            for p in slo_prompts:
                ts = _time.perf_counter()
                rid = fleet.submit(p, cfg["slo_tokens"],
                                   deadline=cfg["slo_deadline"],
                                   on_token=cb)
                submit_t[rid] = ts
                srids.append(rid)
            t0 = _time.perf_counter()
            while fleet.has_work() and \
                    _time.perf_counter() - t0 < 300.0:
                fleet.run_step()
            st = fleet.statuses()
            out = fleet.take_results()
            after = obs.snapshot()
            import numpy as np
            vals = [ttft[r] for r in srids if r in ttft]
            preempts = sum(e.preemptions for e in fleet.engines)
            return {
                "batch": {r: out.get(r, []) for r in brids},
                "slo": {r: out.get(r, []) for r in srids},
                "statuses": {r: st.get(r, "PENDING")
                             for r in brids + srids},
                "slo_ttft_p99_s": round(
                    float(np.quantile(vals, 0.99)), 6) if vals else None,
                "slo_ttft_p50_s": round(
                    float(np.quantile(vals, 0.5)), 6) if vals else None,
                "preemptions": preempts,
                "per_replica": replica_counter_deltas(
                    before, after, _FLEET_REPLICA_FAMILIES),
            }
        finally:
            flags.set_flags(prev)

    on, off = run_arm(True), run_arm(False)
    # the victims' outputs must be bit-identical across arms (replay IS
    # preemption), and every request must end OK in the on-arm
    batch_parity = on["batch"] == off["batch"]
    slo_parity = on["slo"] == off["slo"]
    ok = (batch_parity and slo_parity
          and on["preemptions"] > 0 and off["preemptions"] == 0
          and all(s == "OK" for s in on["statuses"].values())
          and on["slo_ttft_p99_s"] is not None
          and off["slo_ttft_p99_s"] is not None
          and on["slo_ttft_p99_s"] < off["slo_ttft_p99_s"])
    return {
        "preempt_on": {k: v for k, v in on.items()
                       if k not in ("batch", "slo")},
        "preempt_off": {k: v for k, v in off.items()
                        if k not in ("batch", "slo")},
        "victims_bit_identical": batch_parity,
        "slo_bit_identical": slo_parity,
        "slo_ttft_p99_ratio": round(
            on["slo_ttft_p99_s"] / off["slo_ttft_p99_s"], 4)
        if off["slo_ttft_p99_s"] else None,
        "ok": bool(ok)}


def fleet_tiering_section(cfg, seed):
    """Prefix working set > device page budget, host tier absorbing
    the overflow, vs a big-pool no-tier reference."""
    import numpy as np

    import paddle_tpu.observability as obs
    from paddle_tpu.generation.serving import ServingEngine

    model = _fleet_model(cfg)
    rng = np.random.default_rng((seed, 7))
    ps = cfg["page_size"]
    prefixes = [rng.integers(0, cfg["vocab"],
                             (cfg["tier_prefix"],)).astype(np.int32)
                for _ in range(cfg["tier_orgs"])]
    rounds = []
    for rnd in range(cfg["tier_rounds"]):
        for pf in prefixes:
            body = rng.integers(0, cfg["vocab"], (ps,)).astype(np.int32)
            rounds.append(np.concatenate([pf, body]))

    def run_arm(tiered):
        eng = ServingEngine(
            model, max_batch=1, page_size=ps,
            max_seq_len=cfg["max_seq_len"], prefix_cache=True,
            num_pages=(cfg["tier_device_pages"] + 1 if tiered else 256),
            host_tier_pages=(cfg["tier_host_pages"] if tiered else 0),
            replica="tier" if tiered else "ref")
        outs = []
        for p in rounds:
            rid = eng.submit(p.copy(), cfg["max_new"])
            out = eng.run(max_wall=120.0)
            outs.append(out[rid])
        return eng, outs

    before = obs.snapshot()
    ref_eng, ref = run_arm(False)
    tier_eng, tier = run_arm(True)
    after = obs.snapshot()
    pr = replica_counter_deltas(before, after, _FLEET_REPLICA_FAMILIES)
    spills = pr.get("tier", {}).get("prefix_cache_spilled_pages", 0)
    restores = pr.get("tier", {}).get("prefix_cache_restored_pages", 0)
    working_set = (cfg["tier_orgs"]
                   * (-(-cfg["tier_prefix"] // ps) + 1))
    parity = tier == ref
    ok = (parity and spills > 0 and restores > 0
          and working_set > cfg["tier_device_pages"])
    return {
        "device_pages": cfg["tier_device_pages"],
        "host_tier_pages": cfg["tier_host_pages"],
        "prefix_working_set_pages": working_set,
        "spilled_pages": spills, "restored_pages": restores,
        "host_tier_peak_pages": tier_eng._host_tier_peak,
        "requests": len(rounds),
        "parity_bit_identical": parity,
        "ok": bool(ok)}


def bench_fleet(seed, quick=False):
    import jax

    import paddle_tpu.observability as obs

    # routing geometry: prompt = prefix + ONE body token, prefix a
    # page multiple — a warm-cache hit adopts every prefix page and
    # teacher-forces nothing, so TTFT(hit) is one decode step while
    # TTFT(miss) pays the whole monolithic prefill; per-replica pools
    # hold one org's working set comfortably but NOT all orgs', so
    # round-robin placement thrashes eviction at steady state
    cfg = (dict(vocab=256, replicas=3, max_batch=2, page_size=8,
                max_seq_len=128, num_pages=33, orgs=3, per_org=6,
                prefix=120, body=1, max_new=4,
                slo_requests=3, slo_tokens=3, slo_deadline=20.0,
                batch_tokens=48,
                tier_orgs=5, tier_prefix=24, tier_rounds=2,
                tier_device_pages=10, tier_host_pages=64)
           if quick else
           dict(vocab=256, replicas=3, max_batch=2, page_size=8,
                max_seq_len=256, num_pages=79, orgs=4, per_org=10,
                prefix=200, body=1, max_new=6,
                slo_requests=5, slo_tokens=4, slo_deadline=20.0,
                batch_tokens=72,
                tier_orgs=6, tier_prefix=32, tier_rounds=3,
                tier_device_pages=14, tier_host_pages=96))
    sections = {
        "routing": fleet_routing_section(cfg, seed),
        "preemption": fleet_preemption_section(cfg, seed),
        "tiering": fleet_tiering_section(cfg, seed),
    }
    ok = all(s["ok"] for s in sections.values())
    return {
        "schema": FLEET_SCHEMA, "bench": "fleet_load",
        "backend": jax.default_backend(), "seed": seed,
        "config": {k: (list(v) if isinstance(v, tuple) else v)
                   for k, v in cfg.items()},
        "sections": sections,
        "ok": bool(ok),
        "telemetry": obs.snapshot(),
        "memory": obs.memory.section() if obs.enabled() else None,
    }


# ====================================================== spec bench (r16)
SPEC_SCHEMA = 1


def _spec_pair(seed, max_pos):
    """The draft-agreement rig: a 4-layer tiny Llama target whose
    layers >= 1 have o_proj/down_proj scaled by 3e-2 — near-identity
    residual contributions, so the residual stream leaving layer 3 is
    close to the stream leaving layer 0 — plus a 1-layer draft SHARING
    the target's embedding, layer-0, final-norm and head weights. The
    draft is a structural truncation of its target (the production
    speculative-serving shape), so rounds mostly accept but real
    rejections still occur, at a quarter of the target's layer cost."""
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    dims = dict(vocab_size=256, hidden_size=64, num_attention_heads=4,
                num_key_value_heads=2, intermediate_size=128,
                max_position_embeddings=max_pos)
    paddle.seed(seed)
    target = LlamaForCausalLM(LlamaConfig(num_hidden_layers=4, **dims))
    sd = dict(target.state_dict())
    for li in range(1, 4):
        for nm in (f"llama.layers.{li}.self_attn.o_proj.weight",
                   f"llama.layers.{li}.mlp.down_proj.weight"):
            sd[nm] = paddle.to_tensor(sd[nm].numpy() * 3e-2)
    target.set_state_dict(sd)
    paddle.seed(seed + 1)
    draft = LlamaForCausalLM(LlamaConfig(num_hidden_layers=1, **dims))
    dsd = dict(draft.state_dict())
    tsd = target.state_dict()
    for k in dsd:
        if k in tsd:                # embed, layer 0, final norm, head
            dsd[k] = tsd[k]
    draft.set_state_dict(dsd)
    return target, draft


def _spec_engine(target, draft, cfg):
    from paddle_tpu.generation.serving import ServingEngine

    return ServingEngine(target, max_batch=cfg["max_batch"],
                         page_size=cfg["page_size"],
                         max_seq_len=cfg["max_seq_len"],
                         draft_model=draft)


def spec_throughput_section(target, draft, cfg, seed):
    """Batch-1 plain vs speculative A/B: REPEATS measured passes per
    arm after a warmup pass (the warmup's γ ladder climbs through
    every rung, so every draft/verify/sync program the measured passes
    touch is already compiled). Tokens/s is min over passes for BOTH
    arms — the structural rate recurs every pass while a one-off OS
    spike only slows one — and the retrace ledger spans all measured
    passes of both arms."""
    import numpy as np

    import paddle_tpu.observability as obs

    rng = np.random.default_rng((seed, 0))
    prompt = rng.integers(0, cfg["vocab"],
                          (cfg["prompt_len"],)).astype(np.int32)

    def one_pass(use_draft):
        eng = _spec_engine(target, draft if use_draft else None, cfg)
        rid = eng.submit(prompt, cfg["max_new"])
        t0 = time.perf_counter()
        out = eng.run(max_wall=300.0)
        return eng, out[rid], time.perf_counter() - t0, eng.status(rid)

    def run_arm(use_draft):
        one_pass(use_draft)                             # warmup
        before = obs.snapshot()
        walls, statuses = [], []
        for _ in range(REPEATS):
            eng, out, wall, status = one_pass(use_draft)
            walls.append(wall)
            statuses.append(status)
        after = obs.snapshot()
        tps = [round(len(out) / w, 2) for w in walls]
        metrics = {
            "tokens": len(out),
            "passes": REPEATS,
            "wall_s_per_pass": [round(w, 4) for w in walls],
            "tokens_per_s_per_pass": tps,
            "tokens_per_s": min(tps),
            "steady_retraces": trace_total(after) - trace_total(before),
            "all_ok": all(s == "OK" for s in statuses),
        }
        if use_draft:
            acc, rej = eng.spec_tokens_accepted, eng.spec_tokens_rejected
            metrics.update(
                spec_rounds=eng.spec_rounds,
                spec_tokens_accepted=acc, spec_tokens_rejected=rej,
                spec_accept_rate=round(acc / max(1, acc + rej), 4))
        return metrics, out

    plain, plain_out = run_arm(False)
    spec, spec_out = run_arm(True)
    parity = spec_out == plain_out
    speedup = (round(spec["tokens_per_s"] / plain["tokens_per_s"], 4)
               if plain["tokens_per_s"] else None)
    ok = (parity and speedup is not None
          and speedup >= cfg["speedup_bar"]
          and plain["steady_retraces"] == 0
          and spec["steady_retraces"] == 0
          and plain["all_ok"] and spec["all_ok"])
    return {"arms": {"plain": plain, "spec": spec},
            "parity_bit_identical": parity,
            "tokens_per_s_speedup": speedup,
            "speedup_bar": cfg["speedup_bar"],
            "ok": bool(ok)}


def spec_occupancy_section(target, draft, cfg, seed):
    """The γ+1 slot bill: n concurrent rows each cost γ+1 decode slots
    per round, so the largest affordable rung falls as occupancy
    rises. For each row count the sweep records the largest γ any
    round ran at while ALL submitted rows were still live (tail rounds
    after early finishes run at lower occupancy and would pollute the
    reading), and checks the speculative outputs against a plain
    engine on the same prompts — pricing changes the SCHEDULE, never
    the tokens."""
    import numpy as np

    rng = np.random.default_rng((seed, 1))
    prompts = [rng.integers(0, cfg["vocab"],
                            (cfg["prompt_len"],)).astype(np.int32)
               for _ in range(max(cfg["occ_rows"]))]

    rows = []
    for n in cfg["occ_rows"]:
        plain_eng = _spec_engine(target, None, cfg)
        prids = [plain_eng.submit(p, cfg["occ_max_new"])
                 for p in prompts[:n]]
        pout = plain_eng.run(max_wall=300.0)

        eng = _spec_engine(target, draft, cfg)
        rids = [eng.submit(p, cfg["occ_max_new"]) for p in prompts[:n]]
        gamma_full, rounds_full = 0, 0
        while eng.has_work():
            occ = sum(1 for s in eng._slots if s is not None)
            before = eng.spec_rounds
            eng.step()
            if occ == n and eng.spec_rounds > before:
                gamma_full = max(gamma_full, eng.spec_last_gamma)
                rounds_full += 1
        out = eng.results()
        rows.append({
            "rows": n,
            "gamma_at_full_occupancy": gamma_full,
            "rounds_at_full_occupancy": rounds_full,
            "rounds_total": eng.spec_rounds,
            "tokens_accepted": eng.spec_tokens_accepted,
            "tokens_rejected": eng.spec_tokens_rejected,
            "parity_bit_identical":
                [out.get(r, []) for r in rids] ==
                [pout.get(r, []) for r in prids],
        })
    gammas = [r["gamma_at_full_occupancy"] for r in rows]
    top_rung = cfg["rungs"][-1]
    ok = (all(r["parity_bit_identical"] for r in rows)
          and all(a >= b for a, b in zip(gammas, gammas[1:]))
          and gammas[0] == top_rung      # a lone row affords the top
          and gammas[-1] == 0)           # a full batch prices it out
    return {"rows": rows, "gamma_ladder": gammas,
            "top_rung": top_rung, "ok": bool(ok)}


def bench_spec(seed, quick=False):
    import jax

    import paddle_tpu.observability as obs
    from paddle_tpu import flags

    cfg = dict(vocab=256, max_batch=8, page_size=8, max_seq_len=192,
               prompt_len=16, max_new=(48 if quick else 96),
               occ_rows=(1, 2, 4, 8), occ_max_new=(48 if quick else 64),
               spec_slots=16, speedup_bar=1.8)
    target, draft = _spec_pair(31, max_pos=256)
    prev = flags.get_flags(("serving_spec_max_slots",))
    # 16 decode slots make the whole rung ladder reachable: one row
    # affords γ=8 (9 slots), a full batch of 8 affords none
    flags.set_flags({"serving_spec_max_slots": cfg["spec_slots"]})
    try:
        cfg["rungs"] = sorted(
            int(x) for x in
            str(flags.get_flag("serving_spec_rungs")).split(","))
        sections = {
            "throughput": spec_throughput_section(target, draft, cfg,
                                                  seed),
            "occupancy": spec_occupancy_section(target, draft, cfg,
                                                seed),
        }
    finally:
        flags.set_flags(prev)
    ok = all(s["ok"] for s in sections.values())
    return {
        "schema": SPEC_SCHEMA, "bench": "spec_decode",
        "backend": jax.default_backend(), "seed": seed,
        "config": {k: (list(v) if isinstance(v, tuple) else v)
                   for k, v in cfg.items()},
        "sections": sections,
        "ok": bool(ok),
        "telemetry": obs.snapshot(),
        "memory": obs.memory.section() if obs.enabled() else None,
    }


# ================================================= kv-quant bench (r18)
KV_QUANT_SCHEMA = 1


def _kv_quant_model(cfg):
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(1234)
    mcfg = GPTConfig.tiny()
    mcfg.max_position_embeddings = cfg["max_seq_len"]
    return mcfg, GPTForCausalLM(mcfg)


def _kv_quant_engine(model, cfg, kv_dtype, usable_pages):
    from paddle_tpu.generation.serving import ServingEngine

    return ServingEngine(model, max_batch=cfg["max_batch"],
                         page_size=cfg["page_size"],
                         max_seq_len=cfg["max_seq_len"],
                         num_pages=usable_pages + 1,
                         kv_dtype=kv_dtype)


def _kv_quant_drain(eng, prompts, max_new):
    """Submit everything up front and step to drain: how many scheduler
    steps the backlog takes, and the queue-depth integral over them —
    the page-pressure queueing regime made visible as one number."""
    rids = [eng.submit(p, max_new) for p in prompts]
    steps = 0
    queue_steps = 0
    while eng.has_work():
        queue_steps += len(eng._queue)
        eng.step()
        steps += 1
    out = eng.results()
    return {"rids": rids,
            "outputs": [out.get(r, []) for r in rids],
            "statuses": [eng.status(r) for r in rids],
            "steps_to_drain": steps,
            "queue_depth_integral": queue_steps}


def bench_kv_quant(seed, quick=False):
    """The r18 quantized-KV A/B at FIXED pool memory: the bf16/native
    arm's byte budget, re-spent on int8 pages, must buy ~2x (on an f32
    CPU pool: more) the usable page budget — measured from the pool
    LEDGER, never the planner — and the page-pressure queueing regime
    must recede (smaller queue-depth integral, no more drain steps).
    The int8 arm re-runs bit-identically (amax quantization is
    deterministic and write-order independent), the analytic
    ``memwatch plan`` pool term agrees with the ledger within the 10%
    bar, and the retrace ledger stays at zero across the measured
    passes of both arms."""
    import numpy as np

    import jax

    import paddle_tpu.observability as obs
    from paddle_tpu.observability import memory as memwatch

    cfg = (dict(vocab=256, max_batch=8, page_size=8, max_seq_len=128,
                native_pages=9, prompt_len=24, max_new=8, requests=6)
           if quick else
           dict(vocab=256, max_batch=8, page_size=8, max_seq_len=128,
                native_pages=9, prompt_len=24, max_new=8, requests=10))
    mcfg, model = _kv_quant_model(cfg)
    rng = np.random.default_rng((seed, 7))
    prompts = [rng.integers(0, cfg["vocab"],
                            (cfg["prompt_len"],)).astype(np.int32)
               for _ in range(cfg["requests"])]

    # ---- fixed-memory page accounting, ledger-measured: the native
    # arm's pool bytes are the budget; the int8 arm spends the same
    # bytes on quantized pages (int8 payload + f32 per-token scales)
    native_eng = _kv_quant_engine(model, cfg, "native",
                                  cfg["native_pages"])
    nled = native_eng.pool.ledger()
    budget = nled["bytes_per_page"] * nled["usable_pages"]
    int8_probe = _kv_quant_engine(model, cfg, "int8", 1)
    int8_bpp = int8_probe.pool.ledger()["bytes_per_page"]
    int8_pages = budget // int8_bpp
    int8_eng = _kv_quant_engine(model, cfg, "int8", int8_pages)
    iled = int8_eng.pool.ledger()
    ratio = iled["usable_pages"] / nled["usable_pages"]
    pages = {
        "byte_budget": int(budget),
        "native": {"usable_pages": nled["usable_pages"],
                   "bytes_per_page": nled["bytes_per_page"]},
        "int8": {"usable_pages": iled["usable_pages"],
                 "bytes_per_page": iled["bytes_per_page"]},
        "usable_page_ratio": round(ratio, 4),
        # the bf16-pool reference ratio (2-byte payload): what the same
        # A/B yields on chip, where pools store bf16 rather than f32
        "bf16_reference_ratio": round(
            2 * (nled["bytes_per_page"] // 4) / int8_bpp, 4),
    }

    # ---- memwatch plan's analytic pool term vs the measured ledger
    dims = memwatch.ModelDims.of_config(mcfg)
    plan = memwatch.estimate_engine_memory(
        dims, page_size=cfg["page_size"],
        page_budget=iled["usable_pages"], max_batch=cfg["max_batch"],
        max_seq_len=cfg["max_seq_len"], kv_dtype="int8",
        param_count=dims.param_count or sum(
            int(np.prod(v.shape)) for v in model.raw_state()[0].values()))
    ledger_pool_bytes = iled["bytes_per_page"] * (iled["usable_pages"] + 1)
    plan_pool_bytes = plan["breakdown"]["kv_pool"]
    plan_rel_err = plan_pool_bytes / ledger_pool_bytes - 1.0
    planfit = {"plan_kv_pool_bytes": int(plan_pool_bytes),
               "ledger_kv_pool_bytes": int(ledger_pool_bytes),
               "rel_err": round(plan_rel_err, 4),
               "within_10pct": bool(abs(plan_rel_err) <= 0.10)}

    # ---- the queueing A/B: pass 1 warms every program (admission,
    # chunkless prefill, each rung the backlog visits), pass 2 is
    # measured under the zero-retrace bar
    arms = {}
    outputs = {}
    for arm, pages_arm in (("native", nled["usable_pages"]),
                           ("int8", iled["usable_pages"])):
        runs = []
        before = after = None
        for p in range(2):
            eng = _kv_quant_engine(model, cfg, arm, pages_arm)
            if p == 1:
                before = obs.snapshot()
            runs.append(_kv_quant_drain(eng, prompts, cfg["max_new"]))
            if p == 1:
                after = obs.snapshot()
        meas = runs[1]
        arms[arm] = {
            "requests": cfg["requests"],
            "steps_to_drain": meas["steps_to_drain"],
            "queue_depth_integral": meas["queue_depth_integral"],
            "statuses": {s: meas["statuses"].count(s)
                         for s in set(meas["statuses"])},
            "all_ok": all(s == "OK" for s in meas["statuses"]),
            "steady_retraces": trace_total(after) - trace_total(before),
            "rerun_bit_identical": runs[0]["outputs"] == meas["outputs"],
        }
        outputs[arm] = meas["outputs"]

    # cross-arm token agreement is informational: int8 attention is
    # tolerance-bounded, not bit-equal, so greedy argmax may flip —
    # the tolerance contract lives in the kernel parity tests
    agree = [sum(1 for a, b in zip(x, y) if a == b) / max(len(x), 1)
             for x, y in zip(outputs["native"], outputs["int8"])]
    receding = {
        "native_queue_depth_integral":
            arms["native"]["queue_depth_integral"],
        "int8_queue_depth_integral": arms["int8"]["queue_depth_integral"],
        "receded": bool(arms["int8"]["queue_depth_integral"]
                        < arms["native"]["queue_depth_integral"]
                        and arms["int8"]["steps_to_drain"]
                        <= arms["native"]["steps_to_drain"]),
    }
    ok = (ratio >= 1.8
          and planfit["within_10pct"]
          and receding["receded"]
          and all(a["all_ok"] for a in arms.values())
          and all(a["steady_retraces"] == 0 for a in arms.values())
          and arms["int8"]["rerun_bit_identical"]
          and arms["native"]["rerun_bit_identical"])
    return {
        "schema": KV_QUANT_SCHEMA, "bench": "kv_quant",
        "backend": jax.default_backend(), "seed": seed,
        "config": {**cfg, "quick": bool(quick)},
        "pages": pages,
        "plan_vs_ledger": planfit,
        "arms": arms,
        "page_pressure": receding,
        "token_agreement_per_request": [round(a, 4) for a in agree],
        "ok": bool(ok),
        "telemetry": obs.snapshot(),
        "memory": obs.memory.section() if obs.enabled() else None,
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=None,
                    help="bank the ledger JSON here "
                         "(repo convention: SERVING_LOAD_r12.json)")
    ap.add_argument("--per-tenant", type=int, default=16,
                    help="requests per tenant")
    ap.add_argument("--seed", type=int, default=712)
    ap.add_argument("--quick", action="store_true",
                    help="the small deterministic tier-1 slice")
    ap.add_argument("--fleet", action="store_true",
                    help="run the r14 fleet acceptance bench (routing "
                         "A/B + preemption + tiering) instead of the "
                         "single-engine chunked/monolithic A/B")
    ap.add_argument("--spec", action="store_true",
                    help="run the r16 speculative-decoding acceptance "
                         "bench (batch-1 plain-vs-spec throughput A/B "
                         "+ the γ-vs-occupancy ladder) instead of the "
                         "single-engine chunked/monolithic A/B")
    ap.add_argument("--kv-dtype", default=None, choices=("int8",),
                    help="run the r18 quantized-KV acceptance bench: "
                         "native-vs-int8 pool A/B at FIXED pool memory "
                         "(~2x the usable page budget, measured from "
                         "the ledger; page-pressure queueing recedes; "
                         "bit-identical re-runs; zero retraces)")
    args = ap.parse_args()

    doc = (bench_fleet(args.seed, quick=args.quick) if args.fleet
           else bench_spec(args.seed, quick=args.quick) if args.spec
           else bench_kv_quant(args.seed, quick=args.quick)
           if args.kv_dtype
           else bench(args.per_tenant, args.seed, quick=args.quick))
    brief = {k: v for k, v in doc.items() if k != "telemetry"}
    print(json.dumps(brief, indent=2, sort_keys=True))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
    return 0 if doc["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
