"""Seeded Poisson multi-tenant load generator for the serving engine.

The acceptance bench for the r12 production continuous-batching loop:
a deterministic (seeded) open-loop Poisson request stream from several
tenants — a chat tenant with short shared-prefix prompts, a long-prompt
tenant (the decode-stall antagonist), and an SLO tenant submitting with
deadlines — is paced in real time against a ServingEngine, twice:

  chunked      chunked prefill + the bucket ladder (the r12 loop)
  monolithic   whole-prompt prefill, fixed top-rung bucket (pre-r12)

Each arm runs a WARMUP pass first (same prompt-length set, every ladder
rung dispatched) so the measured pass exercises steady state; metrics
come from the r09 telemetry snapshot DELTA across the measured pass:

  - sustained throughput (generated tokens / wall)
  - p50/p99 TTFT and inter-token latency (histogram bucket deltas)
  - ZERO program-cache traces at steady state (the retrace ledger)
  - max decode stall (engine probe): with chunking the worst stall a
    long-prompt arrival imposes on decoding requests is ~one chunk;
    monolithic pays the whole prompt — the artifact asserts
    chunked_max < monolithic_max

plus a cross-arm greedy BIT-IDENTITY check (same schedule, same rids,
same tokens). ``--out SERVING_LOAD_r12.json`` banks the ledger;
``--quick`` is the deterministic tier-1 slice driven by
tests/test_serving_load.py (marker ``serving_load``).
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SCHEMA = 1

# tenant mix: (name, rate req/s, prompt lengths cycled, shared-prefix
# tokens, max_new, deadline seconds or None)
TENANTS = (
    ("chat", 24.0, (12, 24), 8, 12, None),
    ("long", 4.0, (320,), 0, 8, None),
    ("slo", 12.0, (16,), 0, 8, 30.0),
)
QUICK_TENANTS = (
    ("chat", 20.0, (12,), 8, 6, None),
    # long prompts must be long enough that prefill cost is token-work,
    # not dispatch overhead, or the stall comparison loses its margin
    # at tiny-model scale
    ("long", 6.0, (320,), 0, 6, None),
    ("slo", 10.0, (16,), 0, 4, 30.0),
)


def make_arrivals(tenants, per_tenant, vocab, seed):
    """The deterministic request schedule: per-tenant exponential
    inter-arrival gaps and prompt bodies from a private seeded stream
    (tenant prompts share a fixed prefix to exercise the prefix cache),
    merged by arrival time."""
    import numpy as np

    arrivals = []
    for ti, (name, rate, lens, shared, max_new, deadline) in \
            enumerate(tenants):
        rng = np.random.default_rng((seed, ti))
        prefix = rng.integers(0, vocab, (shared,)).astype(np.int32)
        t = 0.0
        for i in range(per_tenant):
            t += float(rng.exponential(1.0 / rate))
            ln = int(lens[i % len(lens)])
            body = rng.integers(0, vocab, (ln - shared,)).astype(np.int32)
            prompt = np.concatenate([prefix, body]).astype(np.int32)
            arrivals.append(dict(t=t, tenant=name, prompt=prompt,
                                 max_new=int(max_new), deadline=deadline))
    arrivals.sort(key=lambda a: (a["t"], a["tenant"]))
    return arrivals


def make_engine(model, arm, cfg):
    from paddle_tpu.generation.serving import ServingEngine

    chunked = arm == "chunked"
    return ServingEngine(
        model, max_batch=cfg["max_batch"], page_size=cfg["page_size"],
        max_seq_len=cfg["max_seq_len"], prefix_cache=True,
        bucket_ladder=(cfg["ladder"] if chunked
                       else (cfg["max_batch"],)),
        prefill_chunk=(cfg["chunk"] if chunked else 0))


def warmup_arm(model, arm, cfg, lens):
    """Compile every program the measured pass can touch: one prefill
    per distinct prompt length (or the chunk program for long ones),
    and one decode dispatch at EVERY ladder rung — a rung first visited
    mid-measurement would read as a steady-state retrace."""
    import numpy as np

    rng = np.random.default_rng(0)
    eng = make_engine(model, arm, cfg)
    for ln in sorted(set(lens)):
        eng.submit(rng.integers(0, cfg["vocab"], (ln,)).astype(np.int32),
                   4)
        eng.run(max_wall=300.0)
    for rung in eng.ladder:
        for _ in range(rung):
            eng.submit(rng.integers(0, cfg["vocab"], (8,))
                       .astype(np.int32), 4)
        eng.run(max_wall=300.0)


def trace_total(snap):
    fam = snap["metrics"].get("program_cache_traces")
    if fam is None:
        return 0.0
    return sum(s["value"] for s in fam["series"])


def hist_delta(before, after, name):
    """Measured-pass histogram view: bucket-wise delta of the two
    cumulative snapshots (min/max dropped — unknown for the window)."""
    fa = after["metrics"].get(name)
    if fa is None or not fa["series"]:
        return None
    sa = fa["series"][0]
    fb = before["metrics"].get(name)
    if fb is None or not fb["series"]:
        return dict(sa)
    sb = fb["series"][0]
    return {"labels": {}, "count": sa["count"] - sb["count"],
            "sum": sa["sum"] - sb["sum"], "buckets": sa["buckets"],
            "counts": [a - b for a, b in zip(sa["counts"], sb["counts"])],
            "min": None, "max": None}


def quantiles(before, after, name, qs=(0.5, 0.99)):
    from paddle_tpu.observability import series_quantile

    entry = hist_delta(before, after, name)
    if entry is None or not entry["count"]:
        return {f"p{int(q * 100)}": None for q in qs}
    return {f"p{int(q * 100)}": round(series_quantile(entry, q), 6)
            for q in qs}


# virtual steps per second: the arrival clock ticks once per scheduler
# round rather than per wall second, so WHICH step each request lands
# on — and therefore whether a long-prompt arrival overlaps live
# decodes — is a pure function of the seed, not of machine load.
# Latencies are still measured in real wall time.
STEPS_PER_SEC = 250


REPEATS = 3     # measured passes per arm: the banked max stall is the
# MIN over passes of each pass's max — the schedule is deterministic,
# so the structural worst stall recurs every pass while a one-off OS/GC
# spike does not (a single pass's max is spike-polluted on shared CPU)


def run_arm(model, arm, cfg, arrivals):
    """The measured passes for one arm: warmed programs, deterministic
    step-indexed pacing, streaming callbacks collecting every token,
    telemetry snapshot delta spanning all passes (so the zero-retrace
    bar covers every pass)."""
    import paddle_tpu.observability as obs

    warmup_arm(model, arm, cfg,
               [len(a["prompt"]) for a in arrivals])
    due = [int(a["t"] * STEPS_PER_SEC) for a in arrivals]

    def one_pass():
        eng = make_engine(model, arm, cfg)
        streamed = {}

        def on_token(rid, tok, done):
            if not done:
                streamed.setdefault(rid, []).append(tok)

        rids = []
        i = 0
        tick = 0
        t0 = time.perf_counter()
        while i < len(arrivals) or eng.has_work():
            while i < len(arrivals) and due[i] <= tick:
                a = arrivals[i]
                rids.append(eng.submit(a["prompt"], a["max_new"],
                                       deadline=a["deadline"],
                                       on_token=on_token))
                i += 1
            tick += 1
            if eng.has_work():
                eng.run_step()
        wall = time.perf_counter() - t0
        return eng, rids, streamed, wall

    before = obs.snapshot()
    walls, stalls = [], []
    for _ in range(REPEATS):
        eng, rids, streamed, wall = one_pass()
        walls.append(wall)
        stalls.append(round(eng.max_decode_stall, 6))
    after = obs.snapshot()

    out = eng.results()
    statuses = [eng.status(r) for r in rids]
    tokens_total = sum(len(out.get(r, [])) for r in rids)
    wall = walls[-1]
    metrics = {
        "requests": len(rids),
        "passes": REPEATS,
        "statuses": {s: statuses.count(s) for s in set(statuses)},
        "all_ok": all(s == "OK" for s in statuses),
        "tokens_total": tokens_total,
        "wall_s": round(wall, 4),
        "tokens_per_s": round(tokens_total / wall, 2) if wall else None,
        "ttft_s": quantiles(before, after, "serving_ttft_seconds"),
        "inter_token_s": quantiles(before, after,
                                   "serving_inter_token_seconds"),
        "prefill_chunk_s": quantiles(before, after,
                                     "serving_prefill_chunk_seconds"),
        "decode_stall_s": quantiles(before, after,
                                    "serving_decode_stall_seconds"),
        "max_decode_stall_s": min(stalls),
        "max_decode_stall_per_pass_s": stalls,
        "steady_retraces": trace_total(after) - trace_total(before),
        "bucket_migrations": eng.bucket_migrations,
        "chunk_dispatches": eng.chunk_dispatches,
        "streamed_matches_results": all(
            streamed.get(r, []) == out.get(r, []) for r in rids),
    }
    return metrics, {r: out.get(r, []) for r in rids}


def bench(per_tenant, seed, quick=False):
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    tenants = QUICK_TENANTS if quick else TENANTS
    cfg = (dict(vocab=256, max_batch=8, page_size=8,
                max_seq_len=384, ladder=(2, 4, 8), chunk=16)
           if quick else
           dict(vocab=256, max_batch=8, page_size=8,
                max_seq_len=512, ladder=(2, 4, 8), chunk=32))
    paddle.seed(1234)
    mcfg = GPTConfig.tiny()
    # the long tenant's prompts need position room beyond tiny's 128
    mcfg.max_position_embeddings = cfg["max_seq_len"]
    model = GPTForCausalLM(mcfg)
    arrivals = make_arrivals(tenants, per_tenant, cfg["vocab"], seed)

    arms = {}
    outputs = {}
    for arm in ("chunked", "monolithic"):
        arms[arm], outputs[arm] = run_arm(model, arm, cfg, arrivals)

    parity = outputs["chunked"] == outputs["monolithic"]
    c_stall = arms["chunked"]["max_decode_stall_s"]
    m_stall = arms["monolithic"]["max_decode_stall_s"]
    stall = {
        "chunked_max_s": c_stall,
        "monolithic_max_s": m_stall,
        # the acceptance bar: the worst stall any decoding request saw
        # shrinks from a whole-prompt prefill to ~one chunk. Both
        # maxima are min-over-passes (the structural stall recurs every
        # pass; a one-off OS/GC spike does not), the long tenant's
        # prompts are 10-20x the chunk so the margin survives ordinary
        # shared-CPU noise, and overlap between a long arrival and live
        # decodes is deterministic (step-indexed pacing), not a race
        # against machine load.
        "ratio": round(c_stall / m_stall, 4) if m_stall else None,
        "bounded_by_chunk": bool(m_stall and c_stall < m_stall),
    }
    ok = (parity
          and stall["bounded_by_chunk"]
          and all(a["all_ok"] for a in arms.values())
          and all(a["steady_retraces"] == 0 for a in arms.values())
          and all(a["streamed_matches_results"] for a in arms.values()))
    import paddle_tpu.observability as obs
    return {
        "schema": SCHEMA, "bench": "serving_load",
        "backend": jax.default_backend(), "seed": seed,
        "config": {**{k: v for k, v in cfg.items()},
                   "ladder": list(cfg["ladder"]),
                   "tenants": [list(t[:2]) + [list(t[2])] + list(t[3:])
                               for t in tenants],
                   "requests_per_tenant": per_tenant,
                   "quick": bool(quick)},
        "arms": arms,
        "parity_bit_identical": parity,
        "stall": stall,
        "ok": bool(ok),
        "telemetry": obs.snapshot(),
        # memwatch: the chunk/ladder programs' compiled-memory rows ride
        # the banked artifact (telemetry_dump --memory renders them)
        "memory": obs.memory.section() if obs.enabled() else None,
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=None,
                    help="bank the ledger JSON here "
                         "(repo convention: SERVING_LOAD_r12.json)")
    ap.add_argument("--per-tenant", type=int, default=16,
                    help="requests per tenant")
    ap.add_argument("--seed", type=int, default=712)
    ap.add_argument("--quick", action="store_true",
                    help="the small deterministic tier-1 slice")
    args = ap.parse_args()

    doc = bench(args.per_tenant, args.seed, quick=args.quick)
    brief = {k: v for k, v in doc.items() if k != "telemetry"}
    print(json.dumps(brief, indent=2, sort_keys=True))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
    return 0 if doc["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
