#!/usr/bin/env python
"""Measure compiled peak/temp memory of the pipeline schedule vs
num_microbatches, remat on/off, and virtual_pp_degree — the evidence for the
"remat == 1F1B activation-memory behavior" claim (pipeline_parallel.py
module docstring): 1F1B's defining property is activation memory bounded by
the number of stages S, not the number of microbatches M. Under XLA autodiff
the scan saves per-tick carries unless the block body is rematerialized, so
remat=True is what bounds the saved-activation footprint.

Writes PIPELINE_MEMORY.md at the repo root. Runs on the CPU-simulated
8-device mesh by default (set JAX_PLATFORMS=tpu to measure on hardware);
XLA's memory accounting (CompiledMemoryStats.temp_size_in_bytes) is the
same machinery either way.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import toolenv  # noqa: E402

toolenv.force_cpu(devices=8)

import numpy as np  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def measure(M, remat, V=1, n_layers=8, hidden=128, seq=128, vocab=128):
    import paddle_tpu as paddle
    from paddle_tpu.distributed.fleet.base_topology import (
        create_hybrid_communicate_group)
    from paddle_tpu.distributed.fleet.meta_parallel import PipelineTrainStep
    from paddle_tpu.models import GPTConfig, GPTForCausalLMPipe
    from paddle_tpu.optimizer import AdamW

    cfg = GPTConfig(vocab_size=vocab, hidden_size=hidden,
                    num_hidden_layers=n_layers, num_attention_heads=4,
                    max_position_embeddings=seq)
    paddle.seed(0)
    pipe = GPTForCausalLMPipe(cfg, num_stages=4)
    hcg = create_hybrid_communicate_group(pp_degree=4)
    step = PipelineTrainStep(pipe, AdamW(learning_rate=1e-3), hcg.get_mesh(),
                             num_microbatches=M, remat=remat,
                             virtual_pp_degree=V, donate=False)
    b = M  # one sample per microbatch keeps compile fast
    x = jnp.zeros((b, seq), jnp.int32)
    y = jnp.zeros((b, seq), jnp.int32)
    lr = jnp.asarray(1e-3, jnp.float32)
    compiled = step._jit_step.lower(
        step.params, step.opt_state, lr, x, y).compile()
    # the one accounting code path: memwatch's section extraction
    from paddle_tpu.observability import memory as memwatch
    return memwatch.stats_from_compiled(compiled)["temp"]


def measure_zbh1(M, n_layers=8, hidden=128, seq=128, vocab=128,
                 schedule="zbh1", time_steps=0):
    """Same model on a pp-only 4-stage mesh, zero-bubble vs lockstep
    (Llama pipe: zbh1 v1 needs untied weights). Returns (temp_bytes,
    median_step_seconds or None)."""
    import time as _time

    import paddle_tpu as paddle
    from jax.sharding import Mesh
    from paddle_tpu.distributed.fleet.meta_parallel import PipelineTrainStep
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLMPipe
    from paddle_tpu.optimizer import AdamW

    cfg = LlamaConfig(vocab_size=vocab, hidden_size=hidden,
                      num_hidden_layers=n_layers, num_attention_heads=4,
                      num_key_value_heads=4, intermediate_size=4 * hidden,
                      max_position_embeddings=seq)
    paddle.seed(0)
    pipe = LlamaForCausalLMPipe(cfg, num_stages=4)
    mesh = Mesh(np.array(jax.devices()[:4]), ("pp",))
    step = PipelineTrainStep(pipe, AdamW(learning_rate=1e-3), mesh,
                             num_microbatches=M, schedule=schedule,
                             donate=False)
    x = jnp.zeros((M, seq), jnp.int32)
    y = jnp.zeros((M, seq), jnp.int32)
    lr = jnp.asarray(1e-3, jnp.float32)
    compiled = step._jit_step.lower(
        step.params, step.opt_state, lr, x, y).compile()
    from paddle_tpu.observability import memory as memwatch
    temp = memwatch.stats_from_compiled(compiled)["temp"]
    try:
        flops = float(compiled.cost_analysis().get("flops", 0.0))
    except Exception:
        flops = 0.0
    med = None
    if time_steps:
        # reuse the AOT executable: the jit dispatch cache is separate,
        # so going through step() would recompile the whole pipeline
        args = (step.params, step.opt_state, lr, x, y)
        jax.block_until_ready(compiled(*args))
        ts = []
        for _ in range(time_steps):
            t0 = _time.perf_counter()
            out = compiled(*args)
            jax.block_until_ready(out)
            ts.append(_time.perf_counter() - t0)
        med = sorted(ts)[len(ts) // 2]
    return temp, med, flops


def zbh1_tick_table():
    """Static-schedule accounting: lockstep executes EVERY stage every
    tick (masked fill/drain work still burns compute), the cond-gated
    zbh1 engine executes only scheduled units. Units per microbatch per
    stage: lockstep 2 (F; B=dx+dw fused by autodiff), zbh1 3 (F; B=dx;
    W=dw)."""
    from paddle_tpu.distributed.fleet.meta_parallel.pipeline_zbh1 import (
        zbh1_schedule)
    rows = []
    for S, M in ((4, 4), (4, 8), (4, 16), (8, 8)):
        Ft, Bt, Wt = zbh1_schedule(S, M)
        T = Ft.shape[0]
        busy = int(((Ft >= 0) | (Bt >= 0) | (Wt >= 0)).sum())
        util = busy / (T * S)
        lock_T = 2 * (M + S - 1)        # F wave + autodiff B wave
        lock_util = M / (M + S - 1)     # active fraction per wave
        rows.append((S, M, T, f"{util:.0%}", lock_T, f"{lock_util:.0%}"))
    return rows


def main():
    rows = []
    for remat in (False, True):
        for M in (4, 8, 16):
            t = measure(M, remat)
            rows.append(("FThenB" if not remat else "remat(1F1B-mem)",
                         M, 1, t))
            print(f"remat={remat} M={M} V=1 temp={t/1e6:.2f} MB",
                  file=sys.stderr)
    for M in (4, 8):
        t = measure(M, True, V=2)
        rows.append(("remat + interleaved", M, 2, t))
        print(f"remat=True M={M} V=2 temp={t/1e6:.2f} MB", file=sys.stderr)
    zb = {}
    zt = {}
    for M in (4, 8):
        zb[M], zm, _zfl = measure_zbh1(M, time_steps=3)
        _, lt, _lfl = measure_zbh1(M, schedule="auto", time_steps=3)
        zt[M] = (zm, lt)
        print(f"zbh1 M={M} temp={zb[M]/1e6:.2f} MB "
              f"step={zm*1e3:.0f} ms vs lockstep {lt*1e3:.0f} ms",
              file=sys.stderr)

    base = {(s, m): t for s, m, v, t in rows if v == 1}
    lines = [
        "# Pipeline schedule: compiled activation (temp) memory",
        "",
        "Evidence for the remat==1F1B memory claim "
        "(`pipeline_parallel.py` docstring): XLA `CompiledMemoryStats."
        "temp_size_in_bytes` of the full fwd+bwd+update pipeline program, "
        "GPT(h=128, L=8, seq=128) on the 8-device CPU mesh, pp=4, "
        "microbatch size 1 (batch scales with M so per-microbatch work is "
        "constant).",
        "",
        "| schedule | M=4 | M=8 | M=16 | growth M4->M16 |",
        "|---|---|---|---|---|",
    ]
    for sched in ("FThenB", "remat(1F1B-mem)"):
        t4, t8, t16 = (base[(sched, m)] for m in (4, 8, 16))
        lines.append(
            f"| {sched} | {t4/1e6:.2f} MB | {t8/1e6:.2f} MB | "
            f"{t16/1e6:.2f} MB | {t16/t4:.2f}x |")
    vpp = {m: t for s, m, v, t in rows if v == 2}
    lines += [
        "",
        "Interleaved (V=2 virtual chunks/device, remat on): "
        + ", ".join(f"M={m}: {t/1e6:.2f} MB" for m, t in sorted(vpp.items()))
        + ".",
        "",
        "Reading: without remat the saved per-tick scan activations grow "
        "with M (the FThenB failure mode the reference's 1F1B schedule "
        "exists to fix); with remat the growth is the microbatch data "
        "itself, activation residency stays bounded by the S in-flight "
        "stage inputs — the 1F1B memory behavior. Regenerate with "
        "`python tools/pipeline_memory.py`.",
        "",
        "## Zero-bubble (ZBH1) vs lockstep",
        "",
        "The lockstep schedules above vmap ONE program over all stages — "
        "fill/drain ticks are masked but still execute, so the bubble "
        "burns real compute. `schedule='zbh1'` "
        "(`pipeline_zbh1.py`) runs per-stage divergent units "
        "(shard_map + cond): F, dx-only B, deferred W — W fills would-be "
        "bubble ticks. Static-schedule accounting (a 'tick' = one unit; "
        "lockstep units are F and the fused autodiff B=dx+dw, so lockstep "
        "does 2 units/microbatch/stage vs zbh1's 3 — zbh1 pays one extra "
        "forward recompute for the split):",
        "",
        "| S | M | zbh1 ticks | zbh1 stage-utilization | lockstep ticks "
        "(2 waves) | lockstep useful fraction |",
        "|---|---|---|---|---|---|",
    ]
    for S, M, T, util, lock_T, lock_util in zbh1_tick_table():
        lines.append(f"| {S} | {M} | {T} | {util} | {lock_T} | "
                     f"{lock_util} |")
    lines += [
        "",
        "Lockstep wastes `(S-1)/(M+S-1)` of every wave in masked compute "
        "(the bubble); zbh1's idle stage-ticks cost ~nothing (cond skips "
        "the unit) and W units absorb the drain. Compiled temp memory of "
        "the zbh1 engine (Llama h=128 L=8, pp-only 4-stage mesh): "
        + ", ".join(f"M={m}: {t/1e6:.2f} MB" for m, t in sorted(zb.items()))
        + " — the M-slot stash buffers (X/Y/G/DX0) trade the lockstep "
        "schedules' scan carries for explicit per-microbatch slots. "
        "Measured CPU-mesh step time (same model/mesh, zbh1 vs lockstep "
        "remat): "
        + ", ".join(f"M={m}: {a*1e3:.0f} ms vs {b*1e3:.0f} ms"
                    for m, (a, b) in sorted(zt.items()))
        + f". zbh1 is slower HERE "
        f"({', '.join(f'M={m}: {a/b - 1:+.0%}' for m, (a, b) in sorted(zt.items()))}) "
        "and the CPU wall clock is load-sensitive (host 'devices' are "
        "threads sharing cores, so it prices TOTAL work under whatever "
        "else the box runs) — use the analytic accounting below, not "
        "these milliseconds, for the schedule decision.",
        "",
        "**Total work, counted from the unit schedule** (XLA "
        "`cost_analysis()` is NOT usable here: it counts a `lax.scan` "
        "body once, not x trip-count — measured zbh1 flops were "
        "identical for M=4 and M=8, the giveaway). Per microbatch per "
        "stage, with F ~ f forward-flops and the backward ~ 2f split "
        "as dx ~ f + dw ~ f: lockstep-remat executes F + recompute-F + "
        "(dx+dw) = 4f; the v1 zbh1 engine executes F + (F+dx) + (F+dw) "
        "= 5f — each of B and W re-runs the stage forward inside its "
        "vjp (`pipeline_zbh1.py` b_unit/w_unit). Ratio 5/4 = 1.25.",
        "",
        "**Projected per-chip time ratio on compute-bound hardware** "
        "(critical path ~ total_work / utilization, utilizations from "
        "the tick table; <1 means zbh1 wins):",
        "",
    ]
    tick = {(S, M): (u, lu) for S, M, _T, u, _lT, lu
            in zbh1_tick_table()}
    for m in sorted(zt):
        zu = float(tick[(4, m)][0].rstrip("%")) / 100
        lu = float(tick[(4, m)][1].rstrip("%")) / 100
        proj = 1.25 * (lu / zu)
        stash = 1.0 * (lu / zu)
        lines.append(
            f"- S=4, M={m}: work ratio 1.25 -> projected {proj:.2f} "
            f"{'(v1 wins)' if proj < 1 else '(v1 loses)'}; a "
            f"stash-residuals W unit (work ratio -> 1.0) projects "
            f"{stash:.2f} ({1 - stash:.0%} win).")
    lines += [
        "",
        "Reading: the v1 recompute-based engine wins only where the "
        "bubble dominates (M close to S); at practical M/S the extra "
        "forward cancels the gain — so `schedule='auto'` stays the "
        "default (refines VERDICT r4 weak #5 from 'plausible but "
        "unproven' to a quantified call). The change that makes zbh1 "
        "win across the table is the one production ZBH1 "
        "implementations use: don't recompute in B/W — stash the "
        "forward's vjp residuals (extractable as arrays with "
        "jax.closure_convert) in per-slot buffers whose depth is the "
        "B/W lag (~S slots of per-stage activation residuals, the 1F1B "
        "in-flight bound, NOT M; the temp budget exists — zbh1's "
        "footprint is 2-4x below lockstep's above). Round-6 engine "
        "change, final validation on-chip (TUNNEL_DIAGNOSIS.md).",
        "",
    ]
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "PIPELINE_MEMORY.md")
    with open(out, "w") as f:
        f.write("\n".join(lines))
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
