#!/usr/bin/env python
"""Trace-discipline lint gate (see paddle_tpu/analysis/tracecheck/).

Usage:
    python tools/tracecheck.py paddle_tpu              # gate (exit 1 on new)
    python tools/tracecheck.py paddle_tpu --json
    python tools/tracecheck.py paddle_tpu --update-baseline
    python tools/tracecheck.py --list-rules

Pure AST — the analyzer is loaded standalone (not through
``paddle_tpu/__init__``), so this runs in ~2 s with no jax import and
no device; safe as a pre-commit hook or bare CI step.  The checked-in
baseline lives at tools/tracecheck_baseline.json; the tier-1 test
(tests/test_tracecheck.py) fails on any finding beyond it.

``python tools/analyze.py`` runs this suite AND meshcheck (MSH001-006,
SPMD collective discipline) AND faultcheck (FLT001-006, recovery
discipline) over one shared parse — prefer it for the full gate.
"""

import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG_DIR = os.path.join(REPO, "paddle_tpu", "analysis", "tracecheck")


def _load_standalone():
    """Import the tracecheck package WITHOUT triggering the framework's
    top-level __init__ (which pulls in jax)."""
    spec = importlib.util.spec_from_file_location(
        "tracecheck", os.path.join(PKG_DIR, "__init__.py"),
        submodule_search_locations=[PKG_DIR])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["tracecheck"] = mod
    spec.loader.exec_module(mod)
    return importlib.import_module("tracecheck.cli")


if __name__ == "__main__":
    sys.exit(_load_standalone().main())
