"""Generate MEMORY_70B.md: the north-star Llama-2-70B program build
(stage3 + mp x pp on a simulated v5p-128) — sharding table + per-device
resident-state accounting + lowering evidence. Run under the test env:

  JAX_PLATFORMS=cpu python tools/memory_70b.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import toolenv  # noqa: E402


def main():
    toolenv.force_cpu()
    import jax
    import numpy as np
    import jax.numpy as jnp
    from paddle_tpu.jax_compat import abstract_mesh

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLMPipe
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel import (
        PipelineTrainStep, _STACK_PREFIX)

    dp, pp, mp, M = 2, 8, 8, 8
    cfg = LlamaConfig.llama2_70b()
    with paddle.LazyGuard():
        pipe = LlamaForCausalLMPipe(cfg, num_stages=pp, tensor_parallel=True)
    n_params = sum(int(np.prod(p.shape)) for p in pipe.parameters())
    mesh = abstract_mesh((dp, pp, mp), ("dp", "pp", "mp"))
    opt = AdamW(1e-4, parameters=pipe.parameters(), weight_decay=0.1,
                multi_precision=True)
    step = PipelineTrainStep(pipe, opt, mesh, num_microbatches=M,
                             remat=True, sharding_level=3,
                             sharding_axis="dp", abstract=True,
                             param_dtype=jnp.bfloat16)

    # per-device resident state via the shared memwatch shard
    # accounting (observability/memory.sharded_param_bytes)
    by = step.per_device_state_bytes()
    b, s = 16, 4096
    from paddle_tpu.jax_compat import abstract_mesh_can_lower
    if abstract_mesh_can_lower():
        lowered = step.lower(jax.ShapeDtypeStruct((b, s), jnp.int32),
                             jax.ShapeDtypeStruct((b, s), jnp.int32))
        text = lowered.as_text()
    else:
        # same version gate as tests/test_llama70b.py: this jax cannot
        # lower an AbstractMesh program; the sharding-table accounting
        # above is jax-version-independent and still banks
        text = ""

    rows = []
    for k in sorted(step.params):
        sds = step.params[k]
        spec = step.param_shardings[k].spec
        ospec = step.opt_shardings[k].spec
        rows.append((k, tuple(sds.shape), str(sds.dtype), str(spec),
                     str(ospec)))

    gb = lambda x: x / 1e9
    out = []
    out.append("# MEMORY_70B — north-star program build evidence\n")
    out.append("Llama-2-70B (`LlamaConfig.llama2_70b()`, "
               f"**{n_params/1e9:.2f}B params**) lowered as ONE jitted "
               "train step — GroupSharded **stage3** + **mp=8 TP** x "
               "**pp=8 pipeline** x **dp=2**, bf16 params + f32 AdamW "
               "master weights, remat on — over a simulated **TPU v5p-128** "
               "(`AbstractMesh((2, 8, 8), ('dp', 'pp', 'mp'))`), lowered "
               "for the real `tpu` platform from a CPU host.\n")
    out.append("Reproduce: `tests/test_llama70b.py` (runs in ~2 s: "
               "LazyGuard meta params mean the 70B program is built "
               "without allocating a single parameter byte).\n")
    out.append("## Per-device resident state (from the sharding table)\n")
    out.append("| component | bytes/device | GB |")
    out.append("|---|---|---|")
    for key in ("params", "slots", "master", "total"):
        out.append(f"| {key} | {by[key]:,} | {gb(by[key]):.2f} |")
    out.append("")
    out.append(f"v5p HBM: 95 GB/chip -> resident state is "
               f"**{by['total']/95e9*100:.1f}%** of HBM; the rest is "
               "activation/remat headroom. Perfect 128-way sharding of the "
               f"14 bytes/param state would be {14*n_params/128/1e9:.2f} "
               "GB/device.\n")
    out.append("## Lowering evidence\n")
    if not text:
        out.append("- SKIPPED on this jax: AbstractMesh lowering is "
                   "version-gated (paddle_tpu.jax_compat."
                   "abstract_mesh_can_lower() is False on 0.4.x) — "
                   "re-run on jax >= 0.6 to regenerate this section.")
    else:
        n_cp = text.count("collective_permute")
        out.append(f"- StableHLO module: {len(text):,} chars, "
                   f"mesh `{'dp=2, pp=8, mp=8'}`, "
                   f"`num_partitions = 128` present: "
                   f"{'num_partitions = 128' in text}")
        out.append(f"- sharding annotations: sdy={'sdy.sharding' in text}, "
                   f"collective_permute sites: {n_cp} (0 is expected pre-"
                   "partitioning: shardy lowers sharding as `sdy` "
                   "annotations and XLA inserts the pp-ring collective-"
                   "permutes during SPMD propagation at compile time)")
        out.append(f"- while/scan loops: {text.count('stablehlo.while')}, "
                   f"dots: {text.count('stablehlo.dot')}")
    out.append("")
    out.append("## Sharding table (param -> (shape, dtype, param spec, "
               "opt-state spec))\n")
    out.append("| param | shape | dtype | param spec | opt spec |")
    out.append("|---|---|---|---|---|")
    for k, shp, dt, spec, ospec in rows:
        out.append(f"| `{k}` | {shp} | {dt} | `{spec}` | `{ospec}` |")
    out.append("")
    out.append("Stacked decoder blocks (`@stacked.*`) carry the pipeline "
               "stack dim sharded over `pp`, Megatron TP over `mp` "
               "(column: q/k/v/gate/up; row: o/down), and the ZeRO-3 "
               "extension over `dp` — params and optimizer state are "
               "sharded over all 128 chips.\n")

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "MEMORY_70B.md")
    with open(path, "w") as f:
        f.write("\n".join(out))
    print(f"wrote {path}")
    print({k: f"{gb(v):.2f} GB" for k, v in by.items()})


if __name__ == "__main__":
    main()
