#!/usr/bin/env python
"""Host-state handoff & serialization-discipline lint gate (see
paddle_tpu/analysis/statecheck/).

Usage:
    python tools/statecheck.py paddle_tpu           # gate (exit 1 on new)
    python tools/statecheck.py paddle_tpu --json    # census included
    python tools/statecheck.py paddle_tpu --update-baseline
    python tools/statecheck.py --list-rules

Pure AST — the analysis package is loaded standalone (never through
``paddle_tpu/__init__``), so this runs in seconds with no jax import
and no device; safe as a pre-commit hook or bare CI step.  The suite
leans on its siblings (the shared tracecheck parse + the bundle
vocabulary faultcheck also imports), so the PARENT analysis package is
what gets loaded, as ``ptanalysis``.

The checked-in baseline lives at tools/statecheck_baseline.json (kept
EMPTY — fix, don't baseline); the tier-1 test
(tests/test_statecheck.py) fails on any finding beyond it.

``python tools/analyze.py`` runs this suite AND tracecheck AND
meshcheck AND faultcheck AND kernelcheck over one shared parse —
prefer it for the full gate.
"""

import importlib
import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ANALYSIS_DIR = os.path.join(REPO, "paddle_tpu", "analysis")


def _load_standalone():
    """Import paddle_tpu.analysis WITHOUT triggering the framework's
    top-level __init__ (which pulls in jax), then hand back the
    statecheck CLI."""
    spec = importlib.util.spec_from_file_location(
        "ptanalysis", os.path.join(ANALYSIS_DIR, "__init__.py"),
        submodule_search_locations=[ANALYSIS_DIR])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["ptanalysis"] = mod
    spec.loader.exec_module(mod)
    return importlib.import_module("ptanalysis.statecheck.cli")


if __name__ == "__main__":
    sys.exit(_load_standalone().main())
