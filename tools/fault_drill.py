"""Chaos drill: inject deterministic faults into serving, training and
data loading, and verify the r10 recovery machinery holds its
invariants under fire.

Arms (each runs a fault-free baseline first, then the chaos pass):

  serving     ServingEngine under the acceptance mix
              ``decode_dispatch:every=5;prefill:p=0.1:seed=7``:
              every request must complete with BIT-IDENTICAL greedy
              tokens vs. the fault-free run, zero wedged requests, and
              the engine must end drained with live pools.
  serving_chunked
              The r12 continuous-batching machinery under fire: long
              prompts through CHUNKED prefill on a 2/4 bucket ladder,
              with ``chunk_prefill`` dying mid-prefill (post-detach,
              before the request has any tokens), ``bucket_migrate``
              dying mid-migration, and decode faults layered on top.
              Same bar: bit-identical greedy continuation (the r10
              replay-recovery guarantee drilled through the new
              sites). Fault schedules are ``times=``-bounded — an
              unbounded ``every=N`` below the chunks-per-prompt count
              is a genuinely wedged backend, which the no-progress
              budget rightly terminates FAILED.
  serving_spec
              The r16 speculative decode mode under fire: a GPT target
              with a divergent draft model (real rejections), with
              ``spec_draft`` dying at the draft dispatch and
              ``spec_verify`` dying BEFORE the accepted-length cursor
              roll. Both sites fire post-detach, so recovery rebuilds
              BOTH pools and replays from host state. The bar is
              double: chaos output bit-identical to the fault-free
              speculative run AND to a plain non-speculative engine
              (the losslessness contract survives injected faults).
  fleet       The r14 multi-replica router under fire: a 2-replica
              ``FleetRouter`` (prefix cache + host-RAM KV tier armed)
              with ``router_dispatch`` killing whole replicas
              (recovery = harvest host-side request state, rebuild the
              replica, re-route the harvest through normal placement
              across the fleet), ``kv_spill`` dying mid-spill/restore,
              and ``preempt`` dying as a victim is unseated — tight-
              deadline arrivals drive real preemptions. Same bar:
              every request OK with BIT-IDENTICAL greedy tokens vs the
              fault-free fleet, ``fleet_replica_losses`` and re-routes
              observed, the fleet drained.
  training    ``Model.fit`` under ``train_dispatch`` faults (+ one
              injected ``checkpoint_save`` failure): training completes,
              the emergency checkpoint lands, the final loss is finite.
  dataloader  process workers under ``dataloader_worker`` deaths:
              the epoch delivers every batch in sampler order through
              restart-with-backoff.

Emits one JSON line per arm and a final combined ledger; ``--out FILE``
banks the ledger (the repo convention: FAULT_DRILL_r10.json). Exit code
0 = every arm green. The short-budget tier-1 slice of this drill lives
in tests/test_faults.py under the ``faults`` marker.
"""

import argparse
import json
import os
import sys
import tempfile
import warnings

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DRILL_SCHEMA = 1
SERVING_SPEC = "decode_dispatch:every=5;prefill:p=0.1:seed=7"
CHUNKED_SPEC = ("chunk_prefill:every=3:times=2;"
                "bucket_migrate:every=2:times=2;"
                "decode_dispatch:every=7:times=2")
TRAIN_SPEC = ("train_dispatch:every=5:times=3;"
              "checkpoint_save:every=1:times=1")
LOADER_SPEC = "dataloader_worker:every=3:times=1"
FLEET_SPEC = ("router_dispatch:every=6:times=2;"
              "kv_spill:every=3:times=2;"
              "preempt:every=1:times=1")
SPEC_DECODE_SPEC = ("spec_verify:every=3:times=2;"
                    "spec_draft:every=5:times=2")


def emit(d):
    print(json.dumps(d), flush=True)


def counters(*names):
    import paddle_tpu.observability as obs
    snap = obs.snapshot()["metrics"]
    out = {}
    for name in names:
        fam = snap.get(name)
        if fam is None:
            continue
        for s in fam["series"]:
            key = name
            if s["labels"]:
                key += "{" + ",".join(
                    f"{k}={v}" for k, v in sorted(s["labels"].items())) + "}"
            out[key] = s.get("value", s.get("count"))
    return out


def delta(after, before):
    return {k: round(v - before.get(k, 0.0), 6)
            for k, v in after.items() if v != before.get(k, 0.0)}


SERVING_COUNTERS = (
    "faults_injected", "serving_recoveries", "serving_retries_total",
    "serving_requests_failed", "serving_requests_timeout",
    "serving_requests_finished")
TRAIN_COUNTERS = (
    "faults_injected", "train_retries_total", "train_recoveries",
    "train_emergency_checkpoints", "train_nan_losses")
LOADER_COUNTERS = ("faults_injected", "io_worker_restarts")
FLEET_COUNTERS = SERVING_COUNTERS + (
    "fleet_replica_losses", "fleet_rerouted_requests",
    "serving_preemptions", "prefix_cache_spilled_pages",
    "prefix_cache_restored_pages")


def drill_serving(n_requests, max_new):
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.generation.serving import ServingEngine
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.testing import faults

    paddle.seed(51)
    model = GPTForCausalLM(GPTConfig.tiny())
    rng = np.random.default_rng(17)
    prompts = [rng.integers(0, model.config.vocab_size,
                            (int(rng.integers(4, 13)),)).astype(np.int32)
               for _ in range(n_requests)]

    def run_engine():
        eng = ServingEngine(model, max_batch=2, page_size=8,
                            max_seq_len=64)
        rids = [eng.submit(p, max_new) for p in prompts]
        out = eng.run(max_wall=300.0)
        return eng, [out[r] for r in rids], [eng.status(r) for r in rids]

    _, baseline, base_status = run_engine()
    before = counters(*SERVING_COUNTERS)
    # wide retry budget: the drill proves bit-identical RECOVERY under
    # sustained chaos; the no-progress FAILED valve is tested on its own
    with faults.armed(SERVING_SPEC, serving_retry_backoff=0.001,
                      serving_max_retries=8):
        eng, chaos, status = run_engine()
    ctr = delta(counters(*SERVING_COUNTERS), before)
    ok = (chaos == baseline
          and all(s == "OK" for s in status)
          and all(s == "OK" for s in base_status)
          and not eng.has_work()
          and all(k is not None for k in eng.pool.k_pages)
          and ctr.get("faults_injected{site=decode_dispatch}", 0) +
          ctr.get("faults_injected{site=prefill}", 0) >= 1)
    row = {"arm": "serving", "ok": ok, "spec": SERVING_SPEC,
           "requests": n_requests, "max_new_tokens": max_new,
           "bit_identical": chaos == baseline,
           "statuses": status, "counters": ctr}
    emit(row)
    return row


def drill_serving_chunked(n_requests, max_new):
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.generation.serving import ServingEngine
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.testing import faults

    paddle.seed(53)
    model = GPTForCausalLM(GPTConfig.tiny())
    rng = np.random.default_rng(19)
    # long prompts: every request takes several chunks at chunk=8
    prompts = [rng.integers(0, model.config.vocab_size,
                            (int(rng.integers(20, 45)),)).astype(np.int32)
               for _ in range(n_requests)]

    def run_engine():
        eng = ServingEngine(model, max_batch=4, page_size=8,
                            max_seq_len=64, bucket_ladder=(2, 4),
                            prefill_chunk=8)
        rids = [eng.submit(p, max_new) for p in prompts]
        out = eng.run(max_wall=300.0)
        return eng, [out[r] for r in rids], [eng.status(r) for r in rids]

    _, baseline, base_status = run_engine()
    before = counters(*SERVING_COUNTERS)
    with faults.armed(CHUNKED_SPEC, serving_retry_backoff=0.001,
                      serving_bucket_patience=2):
        eng, chaos, status = run_engine()
    ctr = delta(counters(*SERVING_COUNTERS), before)
    chunk_fires = ctr.get("faults_injected{site=chunk_prefill}", 0)
    migrate_fires = ctr.get("faults_injected{site=bucket_migrate}", 0)
    ok = (chaos == baseline
          and all(s == "OK" for s in status)
          and all(s == "OK" for s in base_status)
          and not eng.has_work()
          and all(k is not None for k in eng.pool.k_pages)
          and chunk_fires >= 1 and migrate_fires >= 1
          and eng.chunk_dispatches >= 1)
    row = {"arm": "serving_chunked", "ok": ok, "spec": CHUNKED_SPEC,
           "requests": n_requests, "max_new_tokens": max_new,
           "bit_identical": chaos == baseline,
           "statuses": status, "chunk_dispatches": eng.chunk_dispatches,
           "bucket_migrations": eng.bucket_migrations,
           "counters": ctr}
    emit(row)
    return row


def drill_serving_spec(n_requests, max_new):
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.generation.serving import ServingEngine
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.testing import faults

    paddle.seed(61)
    model = GPTForCausalLM(GPTConfig.tiny())
    # a draft with DIFFERENT weights: rounds see real rejections, so
    # the drilled rollback exercises partial-accept cursor rolls
    paddle.seed(62)
    draft = GPTForCausalLM(GPTConfig.tiny())
    rng = np.random.default_rng(29)
    prompts = [rng.integers(0, model.config.vocab_size,
                            (int(rng.integers(4, 13)),)).astype(np.int32)
               for _ in range(n_requests)]

    def run_engine(with_draft=True):
        eng = ServingEngine(model, max_batch=2, page_size=8,
                            max_seq_len=64,
                            draft_model=draft if with_draft else None)
        rids = [eng.submit(p, max_new) for p in prompts]
        out = eng.run(max_wall=300.0)
        return eng, [out[r] for r in rids], [eng.status(r) for r in rids]

    from paddle_tpu import flags as _flags
    prev = {"serving_spec_max_slots": _flags.get_flag(
        "serving_spec_max_slots")}
    # wide slot budget: both decode rows speculate every step, so the
    # every=N fault schedules reach real fires within the drill length
    _flags.set_flags({"serving_spec_max_slots": 6})
    try:
        _, plain, _ = run_engine(with_draft=False)
        beng, baseline, base_status = run_engine()
        before = counters(*SERVING_COUNTERS)
        with faults.armed(SPEC_DECODE_SPEC, serving_retry_backoff=0.001,
                          serving_max_retries=8):
            eng, chaos, status = run_engine()
        ctr = delta(counters(*SERVING_COUNTERS), before)
    finally:
        _flags.set_flags(prev)
    draft_fires = ctr.get("faults_injected{site=spec_draft}", 0)
    verify_fires = ctr.get("faults_injected{site=spec_verify}", 0)
    ok = (chaos == baseline
          and chaos == plain       # losslessness survives the chaos
          and all(s == "OK" for s in status)
          and all(s == "OK" for s in base_status)
          and not eng.has_work()
          and all(k is not None for k in eng.pool.k_pages)
          and all(k is not None for k in eng._draft_pool.k_pages)
          and verify_fires >= 1 and draft_fires >= 1
          and eng.spec_rounds >= 1
          and beng.spec_tokens_rejected >= 1)
    row = {"arm": "serving_spec", "ok": ok, "spec": SPEC_DECODE_SPEC,
           "requests": n_requests, "max_new_tokens": max_new,
           "bit_identical": chaos == baseline,
           "lossless_vs_plain": chaos == plain,
           "statuses": status, "spec_rounds": eng.spec_rounds,
           "tokens_accepted": eng.spec_tokens_accepted,
           "tokens_rejected": eng.spec_tokens_rejected,
           "counters": ctr}
    emit(row)
    return row


def drill_fleet(max_new):
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import flags as _flags
    from paddle_tpu.generation.fleet import FleetRouter
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.testing import faults

    paddle.seed(57)
    model = GPTForCausalLM(GPTConfig.tiny())
    rng = np.random.default_rng(23)
    # shared-prefix orgs whose working set (4 orgs x 4 prompt pages)
    # exceeds the 13-usable-page device pool -> the host tier spills
    # and restores under normal operation, so kv_spill has real fires
    orgs = [rng.integers(0, 256, (24,)).astype(np.int32)
            for _ in range(4)]
    shared = []
    for _ in range(2):
        for pf in orgs:
            body = rng.integers(0, 256, (8,)).astype(np.int32)
            shared.append(np.concatenate([pf, body]))
    long_prompts = [rng.integers(0, 256, (10,)).astype(np.int32)
                    for _ in range(4)]
    tight_prompts = [rng.integers(0, 256, (6,)).astype(np.int32)
                     for _ in range(2)]

    def run_fleet():
        # 10 usable pages/replica vs ~2 orgs x 4 cached pages + a
        # 5-page live request: eviction pressure spills to the host
        # tier in steady state, so kv_spill has real fires
        fleet = FleetRouter(model, replicas=2, max_batch=2, page_size=8,
                            max_seq_len=64, num_pages=11,
                            host_tier_pages=64)
        # saturate every slot with no-deadline long generations first,
        # so the deadline-bearing arrivals below genuinely PREEMPT
        rids = [fleet.submit(p, 24, replica=i % 2)
                for i, p in enumerate(long_prompts)]
        for _ in range(6):
            fleet.run_step()
        rids += [fleet.submit(p, 3, deadline=20.0)
                 for p in tight_prompts]
        rids += [fleet.submit(p, max_new) for p in shared]
        out = fleet.run(max_wall=300.0)
        return fleet, rids, out

    prev = {"serving_preempt_horizon": _flags.get_flag(
        "serving_preempt_horizon")}
    # wide horizon: preemption triggers on queue pressure, not on a
    # wall-clock race the drill box would have to win
    _flags.set_flags({"serving_preempt_horizon": 30.0})
    try:
        bfleet, brids, bout = run_fleet()
        baseline = [bout.get(r) for r in brids]
        base_status = [bfleet.status(r) for r in brids]
        before = counters(*FLEET_COUNTERS)
        with faults.armed(FLEET_SPEC, serving_retry_backoff=0.001,
                          serving_max_retries=8):
            fleet, rids, out = run_fleet()
            chaos = [out.get(r) for r in rids]
            status = [fleet.status(r) for r in rids]
        ctr = delta(counters(*FLEET_COUNTERS), before)
    finally:
        _flags.set_flags(prev)

    def fires(site):
        return ctr.get(f"faults_injected{{site={site}}}", 0)

    # successful-preemption mechanics prove out on the BASELINE fleet
    # (its engines are never rebuilt, so the host probe survives); the
    # chaos arm proves the preempt-fault fire recovers bit-identically
    # — after replay recovery the tight request admits first by slack,
    # so the attempt does not necessarily recur
    base_preempts = sum(e.preemptions for e in bfleet.engines)
    ok = (chaos == baseline
          and all(s == "OK" for s in status)
          and all(s == "OK" for s in base_status)
          and not fleet.has_work()
          and fleet.losses >= 1 and fleet.rerouted >= 1
          and fires("router_dispatch") >= 1
          and fires("kv_spill") >= 1
          and fires("preempt") >= 1
          and base_preempts >= 1)
    row = {"arm": "fleet", "ok": ok, "spec": FLEET_SPEC,
           "requests": len(rids), "max_new_tokens": max_new,
           "bit_identical": chaos == baseline,
           "statuses": status,
           "replica_losses": fleet.losses,
           "rerouted_requests": fleet.rerouted,
           "baseline_preemptions": base_preempts,
           "chaos_preemptions": sum(e.preemptions
                                    for e in fleet.engines),
           "counters": ctr}
    emit(row)
    return row


def drill_training(epochs):
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.hapi.model import Model
    from paddle_tpu.io import Dataset
    from paddle_tpu.testing import faults

    class Reg(Dataset):
        def __len__(self):
            return 32

        def __getitem__(self, i):
            rng = np.random.default_rng(i)
            x = rng.standard_normal(8).astype(np.float32)
            return x, x

    def build():
        paddle.seed(0)
        net = nn.Linear(8, 8)
        m = Model(net)
        m.prepare(
            paddle.optimizer.AdamW(1e-2, parameters=net.parameters()),
            loss=lambda out, y: ((out - y) ** 2).mean())
        return m

    before = counters(*TRAIN_COUNTERS)
    tmp = tempfile.mkdtemp(prefix="fault_drill_")
    with faults.armed(TRAIN_SPEC, train_retry_backoff=0.001):
        m = build()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            m.fit(Reg(), batch_size=4, epochs=epochs, verbose=0,
                  save_dir=tmp, metrics_every=2)
        final = m.evaluate(Reg(), batch_size=4)["loss"]
    ctr = delta(counters(*TRAIN_COUNTERS), before)
    ckpt = os.path.join(tmp, "emergency.pdparams")
    ok = (os.path.exists(ckpt)
          and final is not None and np.isfinite(final)
          and ctr.get("train_recoveries", 0) >= 1
          and ctr.get("faults_injected{site=train_dispatch}", 0) >= 1)
    row = {"arm": "training", "ok": ok, "spec": TRAIN_SPEC,
           "epochs": epochs, "final_eval_loss": float(final),
           "emergency_checkpoint": os.path.exists(ckpt),
           "counters": ctr}
    emit(row)
    return row


def drill_dataloader():
    import numpy as np
    from paddle_tpu.io import DataLoader, Dataset
    from paddle_tpu.testing import faults

    class Rows(Dataset):
        def __len__(self):
            return 40

        def __getitem__(self, i):
            return np.full((4,), i, np.float32)

    before = counters(*LOADER_COUNTERS)
    with faults.armed(LOADER_SPEC, dataloader_max_worker_restarts=16):
        dl = DataLoader(Rows(), batch_size=4, num_workers=2,
                        use_process_workers=True)
        got = [int(np.asarray(b.numpy())[0, 0]) for b in dl]
    ctr = delta(counters(*LOADER_COUNTERS), before)
    ok = (got == list(range(0, 40, 4))
          and ctr.get("io_worker_restarts", 0) >= 1)
    row = {"arm": "dataloader", "ok": ok, "spec": LOADER_SPEC,
           "batches": len(got), "ordered": got == list(range(0, 40, 4)),
           "counters": ctr}
    emit(row)
    return row


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None,
                    help="bank the combined ledger JSON here "
                         "(e.g. FAULT_DRILL_r10.json)")
    ap.add_argument("--requests", type=int, default=8)
    ap.add_argument("--max-new", type=int, default=6)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--arms",
                    default="serving,serving_chunked,serving_spec,"
                            "fleet,training,dataloader")
    args = ap.parse_args()

    import jax
    backend = jax.default_backend()
    arms = {}
    want = args.arms.split(",")
    if "serving" in want:
        arms["serving"] = drill_serving(args.requests, args.max_new)
    if "serving_chunked" in want:
        arms["serving_chunked"] = drill_serving_chunked(
            args.requests, args.max_new)
    if "serving_spec" in want:
        arms["serving_spec"] = drill_serving_spec(
            args.requests, args.max_new)
    if "fleet" in want:
        arms["fleet"] = drill_fleet(args.max_new)
    if "training" in want:
        arms["training"] = drill_training(args.epochs)
    if "dataloader" in want:
        arms["dataloader"] = drill_dataloader()

    ok = all(a["ok"] for a in arms.values())
    ledger = {"schema": DRILL_SCHEMA, "drill": "fault_drill",
              "backend": backend, "ok": ok, "arms": arms}
    emit({"final": True, "ok": ok, "backend": backend})
    if args.out:
        with open(args.out, "w") as f:
            json.dump(ledger, f, indent=2, sort_keys=True)
            f.write("\n")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
