"""Chip sprint: convert a healthy TPU window into banked evidence, in
strict order of leverage-per-minute (VERDICT r3 item 1).

Steps, each in its own subprocess with per-step JSON banking + git commit
(the window may close mid-sequence — everything banked stays banked):

  1. kernels  -> KERNEL_COMPILE_r04.json   compile+run every Pallas kernel
                 fwd+bwd (flash plain/seg/GQA, flash_prefill incl. traced
                 offset, rms_norm), both flash-bwd stat layouts. Minutes;
                 catches Mosaic layout regressions first.
  2. attn     -> ATTN_BENCH_r04.json       flash-vs-dense fwd+bwd 1k..8k + GQA
  3. rmsnorm  -> RMSNORM_BENCH_r04.json    pallas-vs-XLA rms_norm
  4. train    -> BENCH_tpu_r04.json        gpt345m real MFU + decode tok/s
                 (bench.py on the ambient chip; refuses CPU fallbacks)

Run directly (`python tools/chip_sprint.py`) in a healthy window, or let
tools/tpu_watch.py arm it on every healthy probe. `--step NAME` runs one
worker in-process (used by the parent via subprocess). `--test` exercises
the full plumbing on forced-CPU interpret mode without committing (banked
under .cache/) — the pre-chip validation path.
"""
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
import bench as bench_mod

ROUND = os.environ.get("CHIP_SPRINT_ROUND", "r05")
KERNELS_SCHEMA = bench_mod.KERNELS_SCHEMA


def base_env(test_mode: bool) -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    if test_mode:
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("PALLAS_AXON_REMOTE_COMPILE", None)
        # plumbing validation must be CPU-cheap: tiny model, few steps.
        # Workers read the marker so backend checks accept "cpu" and a
        # REAL breakage (bench_error, crash) still fails the validation.
        env["CHIP_SPRINT_TEST"] = "1"
        env.setdefault("BENCH_MODEL", "gpt_tiny")
        env.setdefault("BENCH_STEPS", "3")
    else:
        env.pop("JAX_PLATFORMS", None)  # ambient = TPU via the axon tunnel
    return bench_mod.cache_env(env)


def log(msg: str) -> None:
    print(f"[chip_sprint {time.strftime('%H:%M:%S')}] {msg}", flush=True)


def commit(path: str, msg: str) -> None:
    for attempt in range(5):  # index.lock races with the main session
        r = subprocess.run(["git", "add", path], cwd=REPO,
                           capture_output=True, text=True)
        if r.returncode == 0:
            r = subprocess.run(["git", "commit", "-m", msg, "--", path],
                               cwd=REPO, capture_output=True, text=True)
            if r.returncode == 0:
                log(f"committed {path}")
                return
        log(f"commit attempt {attempt}: {r.stderr.strip()[:200]}")
        time.sleep(10)
    log(f"GAVE UP committing {path} — left in working tree")


# ============================================================= worker steps
def _sync(x) -> None:
    """Host-pull sync: block_until_ready is unreliable through the tunnel."""
    import numpy as np
    np.asarray(jax_leaf(x))


def jax_leaf(x):
    import jax
    leaves = jax.tree_util.tree_leaves(x)
    return leaves[0] if leaves else x


def step_kernels() -> list:
    """Compile + run every Pallas kernel fwd+bwd on the ambient backend.
    Each check reports compile time (first call) and steady-state run time
    separately so a Mosaic regression is attributable per kernel."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from paddle_tpu.kernels.flash_attention import flash_attention_bshd
    from paddle_tpu.kernels.decode_attention import (cached_attention_dense,
                                                     flash_prefill)
    from paddle_tpu.kernels.rms_norm import rms_norm_pallas

    rng = np.random.default_rng(0)
    results = []

    def check(name, fn, *args):
        t0 = time.perf_counter()
        try:
            out = fn(*args)
            _sync(out)
            compile_s = time.perf_counter() - t0
            t1 = time.perf_counter()
            out = fn(*args)
            _sync(out)
            run_s = time.perf_counter() - t1
            rec = {"name": name, "ok": True,
                   "compile_s": round(compile_s, 3),
                   "run_s": round(run_s, 4)}
        except Exception as e:
            rec = {"name": name, "ok": False, "error": repr(e)[:400]}
        rec["backend"] = jax.default_backend()
        results.append(rec)
        log(f"kernel check {name}: {rec}")
        return rec

    b, s, h, d = 2, 512, 8, 64
    mk = lambda *shape: jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
    q, k, v = mk(b, s, h, d), mk(b, s, h, d), mk(b, s, h, d)

    def fwd(q, k, v, **kw):
        return jax.jit(lambda *a: flash_attention_bshd(*a, **kw))(q, k, v)

    def bwd(q, k, v, **kw):
        f = lambda *a: flash_attention_bshd(*a, **kw).astype(jnp.float32).sum()
        return jax.jit(jax.grad(f, argnums=(0, 1, 2)))(q, k, v)

    check("flash_fwd", fwd, q, k, v)
    check("flash_bwd", bwd, q, k, v)

    seg = jnp.asarray(rng.integers(0, 3, (b, s)), jnp.int32)

    def fwd_seg(q, k, v, seg):
        return jax.jit(lambda a, b_, c, s_: flash_attention_bshd(
            a, b_, c, segment_ids=s_))(q, k, v, seg)

    def bwd_seg(q, k, v, seg):
        f = lambda a, b_, c, s_: flash_attention_bshd(
            a, b_, c, segment_ids=s_).astype(jnp.float32).sum()
        return jax.jit(jax.grad(f, argnums=(0, 1, 2)))(q, k, v, seg)

    check("flash_fwd_seg", fwd_seg, q, k, v, seg)
    check("flash_bwd_seg", bwd_seg, q, k, v, seg)

    # GQA (the 70B layout class): 4-D dkv grid, unexpanded kv
    kg, vg = mk(b, s, 2, d), mk(b, s, 2, d)
    check("flash_fwd_gqa", fwd, q, kg, vg)
    check("flash_bwd_gqa", bwd, q, kg, vg)

    # both flash-bwd stat layouts (VERDICT r3 item 4): replicated + compact
    from paddle_tpu import flags as _flags
    try:
        old = _flags.get_flag("flash_compact_stats")
    except KeyError:
        # explicit skip record: an absent flag must not read as "passed"
        results.append({"name": "flash_bwd_compact_stats", "ok": None,
                        "skipped": "flag flash_compact_stats not defined",
                        "backend": jax.default_backend()})
    else:
        try:
            _flags.set_flags({"flash_compact_stats": True})
            check("flash_bwd_compact_stats", bwd, q, k, v)
            check("flash_bwd_compact_stats_gqa", bwd, q, kg, vg)
        finally:
            _flags.set_flags({"flash_compact_stats": old})

    # flash_prefill: static + traced offset, GQA cache
    t_cache = 1024
    kc, vc = mk(b, t_cache, 2, d), mk(b, t_cache, 2, d)
    qp = mk(b, 256, h, d)
    check("flash_prefill", jax.jit(flash_prefill), qp, kc, vc,
          jnp.asarray(512, jnp.int32))

    def parity(ref_fn, got_fn, *args, tol=0.05):
        ref, got = ref_fn(*args), got_fn(*args)
        err = float(jnp.max(jnp.abs(ref.astype(jnp.float32)
                                    - got.astype(jnp.float32))))
        if err >= tol:
            raise AssertionError(f"max_abs_err {err:.5f} >= {tol}")
        return err

    check("flash_prefill_parity_vs_dense", parity,
          lambda *a: cached_attention_dense(*a, 512),
          lambda *a: flash_prefill(*a, 512), qp, kc, vc)

    # rms_norm pallas fwd + bwd (f32: the kernel's reference dtype)
    x = jnp.asarray(rng.standard_normal((b * s, 1024)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((1024,)), jnp.float32)
    check("rms_norm_fwd", jax.jit(rms_norm_pallas), x, w)

    def rms_bwd(x, w):
        f = lambda a, b_: rms_norm_pallas(a, b_).astype(jnp.float32).sum()
        return jax.jit(jax.grad(f, argnums=(0, 1)))(x, w)
    check("rms_norm_bwd", rms_bwd, x, w)

    # paged (block-table) decode attention — the serving-path kernel with
    # scalar-prefetched page index maps (kernels schema 2)
    from paddle_tpu.kernels.paged_attention import (paged_attention,
                                                    paged_attention_xla)
    hkv, page, num_pages = 2, 64, 32
    kp = mk(hkv, num_pages, page, d)
    vp = mk(hkv, num_pages, page, d)
    qd = mk(4, h, d)
    bt = jnp.asarray(rng.permutation(num_pages)[:4 * 8].reshape(4, 8),
                     jnp.int32)
    sl = jnp.asarray([500, 512, 37, 129], jnp.int32)
    check("paged_attention", jax.jit(paged_attention), qd, kp, vp, bt, sl)

    check("paged_attention_parity_vs_xla", parity,
          paged_attention_xla, paged_attention, qd, kp, vp, bt, sl)

    # fused transformer-block decode — the whole-layer serving kernel
    # (kernels schema 4): compile + hidden-state parity vs the jnp
    # composition. Exercises the flat phase grid, scalar-prefetched page
    # maps, in-VMEM rope, and the head-group reshapes KERNEL_DECISIONS.md
    # flags as the Mosaic-layout risk.
    from paddle_tpu.kernels.fused_block_decode import (
        BlockDecodeWeights, fused_block_decode_pallas,
        fused_block_decode_ref)
    import functools as _ft
    hf, nhf, nkvf, inf = 256, 8, 2, 512
    df = hf // nhf
    # 0.05-scaled weights keep the block output O(1): the parity gate is
    # ABSOLUTE (tol 0.05) and bf16 carries ~2-3 significant digits
    mks = lambda *shape: mk(*shape) * 0.05
    wblk = BlockDecodeWeights(
        ln1=mk(hf) * 0.1 + 1.0, wq=mks(hf, nhf * df),
        wk=mks(hf, nkvf * df), wv=mks(hf, nkvf * df),
        wo=mks(nhf * df, hf), ln2=mk(hf) * 0.1 + 1.0,
        wg=mks(hf, inf), wu=mks(hf, inf), wd=mks(inf, hf))
    kpf = mks(nkvf, num_pages, page, df)
    vpf = mks(nkvf, num_pages, page, df)
    xf = mks(4, hf)
    fbd_kw = dict(num_heads=nhf, num_kv_heads=nkvf)
    # lengths stay < mp*page: the appended token must land on an
    # allocated page (the serving engine's allocate() contract)
    slf = jnp.asarray([500, 511, 37, 129], jnp.int32)
    check("fused_block_decode",
          jax.jit(_ft.partial(fused_block_decode_pallas, **fbd_kw)),
          xf, wblk, kpf, vpf, bt, slf)
    check("fused_block_decode_parity_vs_ref", parity,
          lambda *a: fused_block_decode_ref(*a, **fbd_kw)[0],
          lambda *a: fused_block_decode_pallas(*a, **fbd_kw)[0],
          xf, wblk, kpf, vpf, bt, slf)

    # SD-UNet head shapes (kernels schema 3): the flash_attn_min_seqlen
    # 2048->1024 flip newly routes the UNet's seq-1024 self-attention
    # (head_dim 80) through the kernel; seq-4096/d=40 was exercised by
    # the banked SD bench but gets an explicit record here too.
    # Non-causal, like the UNet.
    import functools
    for d_sd, s_sd in ((40, 4096), (80, 1024), (160, 1024)):
        qs = mk(1, s_sd, 8, d_sd)
        ks, vs = mk(1, s_sd, 8, d_sd), mk(1, s_sd, 8, d_sd)
        check(f"flash_fwd_d{d_sd}_s{s_sd}",
              functools.partial(fwd, causal=False), qs, ks, vs)
        check(f"flash_bwd_d{d_sd}_s{s_sd}",
              functools.partial(bwd, causal=False), qs, ks, vs)

    for r in results:
        r["bench_schema"] = KERNELS_SCHEMA
    return results


def step_train_decode() -> list:
    """Run bench.py on the ambient backend; refuse fallbacks."""
    env = dict(os.environ)
    # schema-2 bench adds a pipelined window + 2 batched-decode compiles
    env["BENCH_TIMEOUT"] = env.get("BENCH_TIMEOUT", "4200")
    env["BENCH_PROBE_BUDGET"] = "60"
    # windows flap: bank the 345M MFU + decode number first and leave
    # the SD UNet to its own later step (r05: a wedge cost ~50 min of a
    # live window; never put two compiles between us and an artifact)
    env["BENCH_SD"] = "0"
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       env=env, capture_output=True, text=True, timeout=4500)
    lines = []
    for ln in r.stdout.splitlines():
        try:
            lines.append(json.loads(ln))
        except (json.JSONDecodeError, ValueError):
            continue
    if not lines:
        raise RuntimeError(f"bench.py produced no JSON: rc={r.returncode} "
                           f"{r.stderr[-1500:]}")
    # backend/fallback validation happens centrally in require_tpu
    return [lines[-1]]


def step_tune() -> list:
    """345M train-only batch sweep: the banked MFU runs batch=8; HBM has
    headroom (≈4.8 GB optimizer+param state of 16 GB), and a larger
    per-step token count amortizes weight loads. One JSON line per
    candidate; step_train's artifact stays the primary number."""
    out = []
    for batch in (16, 24):
        env = dict(os.environ)
        env["BENCH_SD"] = "0"
        env["BENCH_DECODE"] = "0"       # train-only: 1 compile per point
        env["BENCH_BATCH"] = str(batch)
        env["BENCH_PROBE_BUDGET"] = "60"
        # bigger batches compile+run longer than the batch-8 default run
        env["BENCH_TIMEOUT"] = env.get("BENCH_TIMEOUT", "2100")
        try:
            r = subprocess.run(
                [sys.executable, os.path.join(REPO, "bench.py")], env=env,
                capture_output=True, text=True, timeout=2400)
            lines = []
            for ln in r.stdout.splitlines():
                try:
                    lines.append(json.loads(ln))
                except (json.JSONDecodeError, ValueError):
                    continue
            rec = lines[-1] if lines else {}
            rec["tune_batch"] = batch
            # bench.py's parent ALWAYS exits 0 and always prints one JSON
            # line; a failed point surfaces as metric=bench_error (no
            # backend). Mark it a failed check rather than letting the
            # backend-less line poison the whole artifact in require_tpu.
            ok_backends = (("tpu", "axon", "cpu")
                           if os.environ.get("CHIP_SPRINT_TEST") == "1"
                           else ("tpu", "axon"))
            if (not lines or r.returncode != 0
                    or rec.get("metric") == "bench_error"
                    or rec.get("backend") not in ok_backends):
                rec["ok"] = False
                rec.setdefault("error", f"rc={r.returncode} "
                                        f"{r.stderr[-400:]}")
        except Exception as e:   # timeout/OSError: bank as a failed check
            rec = {"tune_batch": batch, "ok": False, "error": repr(e)[:300]}
        out.append(rec)
    return out


def maybe_flip_bench_batch() -> None:
    """If a swept batch beats the banked batch-8 MFU by >5%, make it the
    bench default (same banked-decision pattern as the compact-stats
    flip)."""
    tune_path = os.path.join(REPO, f"TRAIN_TUNE_{ROUND}.json")
    bench_path = os.path.join(REPO, f"BENCH_tpu_{ROUND}.json")
    if not (os.path.exists(tune_path) and os.path.exists(bench_path)):
        return
    with open(tune_path) as f:
        tune = json.load(f)["results"]
    with open(bench_path) as f:
        base = json.load(f)["results"][-1]
    base_mfu = base.get("value") or 0
    cands = [(r.get("value") or 0, r.get("tune_batch"))
             for r in tune if r.get("ok") is not False
             and r.get("unit") == "mfu_fraction"]
    if not cands:
        return
    best_mfu, best_batch = max(cands)
    if best_mfu <= base_mfu * 1.05:
        log(f"bench-batch flip: gate not met (best {best_mfu} @ "
            f"{best_batch} vs banked {base_mfu} @ 8)")
        return
    bench_py = os.path.join(REPO, "bench.py")
    # the flip auto-commits bench.py wholesale: refuse when unrelated
    # uncommitted edits would be swept into the commit (the decision
    # stays banked in the tune artifact for manual application)
    dirty = subprocess.run(["git", "diff", "--quiet", "--", "bench.py"],
                           cwd=REPO).returncode != 0
    if dirty:
        log("bench-batch flip: bench.py has uncommitted edits — skipping "
            f"(banked decision: batch {best_batch} @ {best_mfu:.4f} MFU)")
        return
    with open(bench_py) as f:
        src = f.read()
    old = 'batch = int(os.environ.get("BENCH_BATCH", "8"))'
    if old not in src:
        log("bench-batch flip: default already changed or moved")
        return
    import re as _re
    m = _re.search(r"BENCH_SCHEMA = (\d+)", src)
    if not m:
        log("bench-batch flip: BENCH_SCHEMA marker missing — skipping")
        return
    # changing the measured default IS a measurement-semantics change:
    # bump the schema so the banked batch-8 train artifact goes
    # stale_schema and re-banks at the new default on the next window
    src = src.replace(m.group(0), f"BENCH_SCHEMA = {int(m.group(1)) + 1}")
    src = src.replace(
        old, f'batch = int(os.environ.get("BENCH_BATCH", "{best_batch}"))')
    with open(bench_py, "w") as f:
        f.write(src)
    commit(bench_py,
           f"Default 345M bench batch -> {best_batch}: measured "
           f"{best_mfu:.4f} vs {base_mfu:.4f} MFU at batch 8 on chip "
           f"(TRAIN_TUNE_{ROUND}.json); bench schema bumped so the train "
           "artifact re-banks at the new default")
    log(f"bench-batch flip: APPLIED ({best_batch}, {best_mfu:.4f} MFU)")


def step_sd() -> list:
    """SD-1.5 UNet train-step bench (BASELINE configs[4]) on the ambient
    backend, split out of the train step so the flagship MFU artifact
    never waits behind a second large compile."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.flags import is_tpu_backend

    rec = bench_mod._sd_unet_bench(paddle, jax, is_tpu_backend())
    rec["backend"] = jax.default_backend()
    rec["bench_schema"] = bench_mod.BENCH_SCHEMA
    return [rec]


STEPS = {
    "kernels": (f"KERNEL_COMPILE_{ROUND}.json", step_kernels, 2400),
    "attn": (f"ATTN_BENCH_{ROUND}.json", None, 2700),      # tools/attn_bench
    "rmsnorm": (f"RMSNORM_BENCH_{ROUND}.json", None, 1800),
    "train": (f"BENCH_tpu_{ROUND}.json", step_train_decode, 4800),
    # SD15's UNet compile through the tunnel alone can eat ~35 min; the
    # r05 window lost two 40-min slots to mid-compile timeouts
    "sd": (f"SD_BENCH_{ROUND}.json", step_sd, 5400),
    # where does the 345M step time GO: jax.profiler capture + XPlane
    # category/top-op breakdown (compile cached by the train step)
    "profile": (f"PROFILE_{ROUND}.json", None, 2400),
    # batch sweep: two train-only bench points above the banked batch 8
    "tune": (f"TRAIN_TUNE_{ROUND}.json", step_tune, 5400),
    # Llama-2-7B int8 serving on the single chip: the streaming-quantize
    # path (13.4 GB bf16 model -> 6.6 GB int8 without ever holding the
    # dense weights) + paged-KV decode at batch 1 and 8
    "decode7b": (f"DECODE7B_{ROUND}.json", None, 5400),
}
_TOOL_SCRIPTS = {"attn": "attn_bench.py", "rmsnorm": "rmsnorm_bench.py",
                 "profile": "train_profile.py",
                 "decode7b": "decode7b_bench.py"}


def run_worker(step: str) -> None:
    """Child mode: run one step in-process, print JSON lines to stdout."""
    _, fn, _ = STEPS[step]
    if fn is None:
        raise SystemExit(f"step {step!r} runs via tools/"
                         f"{_TOOL_SCRIPTS[step]} — no in-process worker")
    for rec in fn():
        print(json.dumps(rec), flush=True)


def require_tpu(lines: list, test_mode: bool) -> None:
    """Every SUCCESS record must come from the real chip. ok:False
    failure records carry no measurement — they are counted as failed
    checks (bounded retries) rather than poisoning the whole artifact."""
    if test_mode:
        return
    bad = [l.get("backend") for l in lines
           if l.get("ok") is not False
           and l.get("backend") not in ("tpu", "axon")]
    if bad:
        raise RuntimeError(f"step ran on {bad[0]!r}, not TPU — not banking")
    fb = [l for l in lines if l.get("fallback")]
    if fb:
        raise RuntimeError(f"step self-reported a fallback "
                           f"({fb[0]['fallback']}) — not banking")


def _bump_retry(artifact: str) -> int:
    """Failed-check re-runs per artifact, persisted across sprint arms
    (each arm is a fresh process — an in-memory count would reset)."""
    path = os.path.join(REPO, ".cache", "sprint_retries.json")
    try:
        with open(path) as f:
            counts = json.load(f)
    except (OSError, ValueError):
        counts = {}
    counts[artifact] = counts.get(artifact, 0) + 1
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(counts, f)
    return counts[artifact]


def run_step(step: str, test_mode: bool) -> bool:
    """Run one sprint step in a subprocess; bank + commit its artifact.
    Returns True on success."""
    artifact, fn, timeout = STEPS[step]
    out_dir = os.path.join(REPO, ".cache") if test_mode else REPO
    path = os.path.join(out_dir, artifact)
    if os.path.exists(path):
        state = bench_mod.artifact_state(path)
        if test_mode:  # validation must never pass on a stale artifact
            os.remove(path)
        elif state == "banked":
            log(f"{artifact} already banked — skipping")
            return True
        elif state == "stale_schema":
            # measurement semantics improved since this was banked: always
            # re-bench on a healthy window (no retry ledger — that bound
            # exists for persistent per-check FAILURES, and a schema-stale
            # artifact is healthy evidence, just measured the old way).
            # Overwrite-on-success keeps the old artifact until then.
            log(f"{artifact} banked under an older bench schema — "
                "re-benching")
        elif _bump_retry(artifact) > 2:
            # a PERSISTENT per-check failure is real evidence, not a
            # window flap — stop burning perishable windows on it (the
            # count persists across sprint arms in .cache)
            log(f"{artifact} has failed checks but retries are "
                "exhausted — keeping it as-is")
            return True
        else:
            # per-check failures may be a window flap, not a real kernel
            # bug — re-run. The old artifact stays on disk until the
            # re-run SUCCEEDS (overwrite-on-success): a window dying
            # mid-re-run must not erase banked evidence
            log(f"{artifact} has failed checks — re-running")
    if step in _TOOL_SCRIPTS:
        argv = [sys.executable,
                os.path.join(REPO, "tools", _TOOL_SCRIPTS[step])]
    else:
        argv = [sys.executable, os.path.abspath(__file__), "--step", step]
    log(f"step {step} -> {artifact} ...")
    env = base_env(test_mode)
    # stream the step's output to files so a wedged step is diagnosable
    # while it runs (capture_output showed nothing until completion)
    cache_dir = os.path.join(REPO, ".cache")
    os.makedirs(cache_dir, exist_ok=True)
    out_log = os.path.join(cache_dir, f"sprint_{step}.out")
    err_log = os.path.join(cache_dir, f"sprint_{step}.err")
    try:
        with open(out_log, "w") as of, open(err_log, "w") as ef:
            r = subprocess.run(argv, env=env, stdout=of, stderr=ef,
                               text=True, timeout=timeout, cwd=REPO)
        with open(out_log) as f:
            stdout = f.read()
        with open(err_log) as f:
            stderr = f.read()
        lines = []
        for ln in stdout.splitlines():
            try:
                lines.append(json.loads(ln))
            except (json.JSONDecodeError, ValueError):
                continue
        if r.returncode != 0 or not lines:
            raise RuntimeError(f"rc={r.returncode} lines={len(lines)} "
                               f"stderr={stderr[-2000:]}")
        require_tpu(lines, test_mode)
        bad = [l for l in lines if l.get("ok") is False]
        if test_mode and bad:
            # validation is STRICT: a failed check in --test is a real
            # plumbing regression, not a window flap
            raise RuntimeError(f"--test found failed checks: "
                               f"{[b.get('error', b) for b in bad]!r}"[:600])
        payload = {"step": step, "backend": lines[-1].get("backend"),
                   "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
                   "n_failed_checks": len(bad), "results": lines}
        os.makedirs(out_dir, exist_ok=True)
        with open(path, "w") as f:
            json.dump(payload, f, indent=1)
        if not test_mode:
            commit(path, f"Bank on-chip {step} sprint artifact ({ROUND})")
        log(f"step {step} banked ({len(lines)} records, "
            f"{len(bad)} failed checks)")
        return True
    except Exception as e:
        log(f"step {step} FAILED: {e!r}"[:600])
        return False


def maybe_flip_compact_stats() -> None:
    """Execute the banked decision tree (KERNEL_DECISIONS.md): if the
    kernels artifact proves both compact-stat bwd layouts compile on a
    real chip, flip FLAGS_flash_compact_stats default to True and commit
    — the window converts straight into the decision."""
    path = os.path.join(REPO, f"KERNEL_COMPILE_{ROUND}.json")
    if not os.path.exists(path):
        return
    with open(path) as f:
        payload = json.load(f)
    recs = {r.get("name"): r for r in payload.get("results", [])}
    need = ("flash_bwd_compact_stats", "flash_bwd_compact_stats_gqa")
    # the chip presents as backend "axon" through the tunnel plugin and
    # "tpu" when native — both are the real Mosaic compile path
    if not all(recs.get(n, {}).get("ok") is True
               and recs.get(n, {}).get("backend") in ("tpu", "axon")
               for n in need):
        log("compact-stats flip: gate not met (see KERNEL_DECISIONS.md)")
        return
    flags_py = os.path.join(REPO, "paddle_tpu", "flags.py")
    with open(flags_py) as f:
        src = f.read()
    old = 'define_flag("flash_compact_stats", False,'
    if old not in src:
        log("compact-stats flip: default already flipped or moved")
        return
    with open(flags_py, "w") as f:
        f.write(src.replace(old,
                            'define_flag("flash_compact_stats", True,'))
    commit(flags_py,
           "Flip flash_compact_stats default on: Mosaic layouts validated "
           f"on chip ({ROUND} kernels artifact; KERNEL_DECISIONS.md)")
    log("compact-stats flip: APPLIED and committed")


def main() -> int:
    if "--step" in sys.argv:
        run_worker(sys.argv[sys.argv.index("--step") + 1])
        return 0
    test_mode = "--test" in sys.argv
    # train (real MFU, the north star) immediately after the kernel
    # existence proof: windows are perishable and the microbenches are
    # the cheapest thing to lose (r05: the attn step wedged a live
    # window for its full timeout with train still unbanked behind it)
    order = ["kernels", "train", "attn", "rmsnorm", "sd", "profile",
             "tune", "decode7b"]
    if test_mode:
        # plumbing validation for every step with new code paths; the
        # attn/rmsnorm tools predate the sprint and train is the bench's
        # own --test-free path (TPU-priced end to end)
        order = ["kernels", "profile", "tune", "decode7b"]
    ok = True
    for step in order:
        if not run_step(step, test_mode):
            ok = False
            if test_mode:
                break
            # one step can wedge (stuck claim/RPC) while the window is
            # fine — probe cheaply; only a dead window ends the sprint
            state = bench_mod._probe_with_backoff(base_env(False))
            if state != "tpu":   # the probe maps a healthy axon tunnel
                                 # to "tpu" already (bench._probe_backend)
                log(f"window dead after {step} failure (probe={state}) — "
                    "ending sprint")
                break
            log(f"window still healthy after {step} failure — continuing")
            continue
        if step == "kernels" and not test_mode:
            try:
                maybe_flip_compact_stats()
            except Exception as e:   # the flip must never kill the sprint
                log(f"compact-stats flip FAILED: {e!r}"[:400])
        if step == "tune" and not test_mode:
            try:
                maybe_flip_bench_batch()
            except Exception as e:   # the flip must never kill the sprint
                log(f"bench-batch flip FAILED: {e!r}"[:400])
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
