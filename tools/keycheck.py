#!/usr/bin/env python
"""Compiled-program identity & cache-key soundness lint gate (see
paddle_tpu/analysis/keycheck/).

Usage:
    python tools/keycheck.py paddle_tpu           # gate (exit 1 on new)
    python tools/keycheck.py paddle_tpu --json    # key census included
    python tools/keycheck.py paddle_tpu --update-baseline
    python tools/keycheck.py --list-rules

Pure AST — the analysis package is loaded standalone (never through
``paddle_tpu/__init__``), so this runs in seconds with no jax import
and no device; safe as a pre-commit hook or bare CI step.  The suite
leans on its siblings (the shared tracecheck parse, statecheck's
device-expression vocabulary, and the jax-free key_vocab the serving
engine imports back), so the PARENT analysis package is what gets
loaded, as ``ptanalysis``.

The checked-in baseline lives at tools/keycheck_baseline.json (kept
EMPTY — fix, don't baseline); the tier-1 test (tests/test_keycheck.py)
fails on any finding beyond it.

``python tools/analyze.py`` runs this suite AND tracecheck AND
meshcheck AND faultcheck AND kernelcheck AND statecheck over one
shared parse — prefer it for the full gate.
"""

import importlib
import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ANALYSIS_DIR = os.path.join(REPO, "paddle_tpu", "analysis")


def _load_standalone():
    """Import paddle_tpu.analysis WITHOUT triggering the framework's
    top-level __init__ (which pulls in jax), then hand back the
    keycheck CLI."""
    spec = importlib.util.spec_from_file_location(
        "ptanalysis", os.path.join(ANALYSIS_DIR, "__init__.py"),
        submodule_search_locations=[ANALYSIS_DIR])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["ptanalysis"] = mod
    spec.loader.exec_module(mod)
    return importlib.import_module("ptanalysis.keycheck.cli")


if __name__ == "__main__":
    sys.exit(_load_standalone().main())
