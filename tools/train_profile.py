"""Profile the GPT-345M train step on the ambient backend and summarize
where the step time goes (MFU diagnosis — BASELINE.md north star).

Captures a jax.profiler trace around a few steps, parses the XPlane proto
dumped under --out, and prints a per-op-category time breakdown as JSON
lines (matmul vs attention kernel vs elementwise vs copy/infeed), plus the
top-N individual ops. Works through the axon tunnel: device traces may be
unavailable there, in which case it falls back to a wall-clock phase split
(dispatch vs host-sync) that still separates tunnel RTT from compute.

Usage: python tools/train_profile.py [--steps 6] [--out .cache/profile]
Env: BENCH_MODEL/BENCH_BATCH/BENCH_SEQ as bench.py.
"""
import glob
import json
import os
import sys
import time

_BACKEND = "unknown"


def emit(d: dict) -> None:
    """Print one JSON line; every line carries the backend because
    chip_sprint's require_tpu validates ALL lines of a banked artifact."""
    d.setdefault("backend", _BACKEND)
    print(json.dumps(d), flush=True)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    steps = 6
    out = os.path.join(REPO, ".cache", "profile")
    argv = sys.argv[1:]
    if "--steps" in argv:
        steps = int(argv[argv.index("--steps") + 1])
    if "--out" in argv:
        out = argv[argv.index("--out") + 1]

    import numpy as np

    import jax
    import paddle_tpu as paddle
    import bench as bench_mod

    global _BACKEND
    _BACKEND = jax.default_backend()
    emit({"phase": "init", "devices": [str(d) for d in jax.devices()]})

    # bench.py's recipe verbatim, so the profiled step IS the benchmarked
    # step (same dtype policy, master weights, remat knob)
    cfg, batch, seq, build, on_tpu = bench_mod.build_train_setup(
        os.environ.get("BENCH_MODEL", "gpt345m"))
    remat = os.environ.get("BENCH_REMAT", "0") == "1"
    model, step = build(remat)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch, seq + 1))
    x = paddle.to_tensor(ids[:, :-1].astype(np.int32))
    y = paddle.to_tensor(ids[:, 1:].astype(np.int32))

    # ALL step calls run under the same auto_cast as bench.py's measured
    # loop: the traced program must be the benchmarked program (and must
    # hit the persistent compile cache the train step warmed)
    amp = lambda: paddle.amp.auto_cast(enable=on_tpu, level="O1",
                                       dtype="bfloat16")
    t0 = time.perf_counter()
    with amp():
        float(step(x, y))   # compile + one step
    emit({"phase": "compile", "s": round(time.perf_counter() - t0, 2)})

    # wall-clock phase split: per-step synced vs pipelined — the
    # pipelined side is the trainer's own async window (dispatch without
    # blocking, TrainStep.sync() as the closing barrier), so the split
    # measures exactly what Model.fit's async-by-default loop removes
    with amp():
        for _ in range(2):
            step(x, y)
            step.pull_metrics(lag=0)
        t0 = time.perf_counter()
        for _ in range(steps):
            step(x, y)
            step.pull_metrics(lag=0)   # metrics_every=1: per-step sync
        synced = (time.perf_counter() - t0) / steps
        # the pipelined arm must fit in the dispatch window: a throttled
        # call host-syncs inside __call__ and would be banked as
        # "pipelined" time (bench.py asserts the same invariant)
        step.max_in_flight = max(step.max_in_flight, steps)
        t0 = time.perf_counter()
        for _ in range(steps):
            step(x, y)
        step.sync()
        piped = (time.perf_counter() - t0) / steps
    emit({"phase": "wallclock", "synced_step_s": round(synced, 4),
          "pipelined_step_s": round(piped, 4),
          "per_step_sync_overhead_s": round(synced - piped, 4),
          "step_traces": step.trace_count,
          "step_throttles": step.throttle_count})

    # device trace. Only files CREATED BY THIS RUN count — a stale dump
    # from an earlier (possibly CPU) run must never be summarized and
    # banked as this run's evidence. Errors emit ok:false so the sprint's
    # failed-check retry machinery re-runs the step on a later window.
    os.makedirs(out, exist_ok=True)
    pattern = os.path.join(out, "**", "*.xplane.pb")
    before = set(glob.glob(pattern, recursive=True))
    try:
        with jax.profiler.trace(out), amp():
            for _ in range(steps):
                loss = step(x, y)
            float(loss)
    except Exception as e:
        emit({"phase": "trace", "ok": False, "error": repr(e)[:300]})
        return 0

    fresh = sorted(set(glob.glob(pattern, recursive=True)) - before,
                   key=os.path.getmtime)
    if not fresh:
        emit({"phase": "trace", "ok": False, "error": "no xplane dumped"})
        return 0
    summarize_xplane(fresh[-1], steps)
    return 0


def _categorize(name: str) -> str:
    n = name.lower()
    if "custom-call" in n or "pallas" in n or "flash" in n:
        return "pallas/custom"
    if "fusion" in n:
        return "fusion"
    # "convert" (dtype cast) must not hit the "conv"olution check: casts
    # around bf16/f32 master weights are exactly the overhead this tool
    # exists to surface
    if any(k in n for k in ("copy", "transpose", "bitcast", "reshape",
                            "convert")):
        return "copy/layout"
    if "convolution" in n or "dot" in n or "matmul" in n or "einsum" in n:
        return "matmul"
    if any(k in n for k in ("all-reduce", "all-gather", "reduce-scatter",
                            "collective", "permute")):
        return "collective"
    if any(k in n for k in ("infeed", "outfeed", "transfer")):
        return "host-transfer"
    return "other"


def _read_varint(buf, i):
    r = 0
    s = 0
    while True:
        b = buf[i]
        i += 1
        r |= (b & 0x7F) << s
        if not b & 0x80:
            return r, i
        s += 7


def _fields(buf):
    """Yield (field_no, wire_type, value_bytes_or_int) of a proto message."""
    i = 0
    n = len(buf)
    while i < n:
        key, i = _read_varint(buf, i)
        fno, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _read_varint(buf, i)
            yield fno, wt, v
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            yield fno, wt, buf[i:i + ln]
            i += ln
        elif wt == 5:
            yield fno, wt, int.from_bytes(buf[i:i + 4], "little")
            i += 4
        elif wt == 1:
            yield fno, wt, int.from_bytes(buf[i:i + 8], "little")
            i += 8
        else:  # unsupported group etc.
            return


def summarize_xplane(path: str, steps: int) -> None:
    """Minimal XPlane proto walk (no tensorboard dependency): decode the
    XSpace wire format enough to sum event durations per TPU op name."""
    with open(path, "rb") as f:
        space = f.read()
    # XSpace: repeated XPlane planes = 1. Device planes ("/device:TPU:0")
    # exist for real-chip captures; CPU (and possibly the tunnel) only dump
    # the "/host:CPU" plane, whose XLA op executions still carry op names —
    # summarize every plane separately and let the reader pick.
    per_plane = {}
    for fno, wt, plane in _fields(space):
        if fno != 1 or wt != 2:
            continue
        # XPlane: name=2(str), lines=3, event_metadata=11 (map<int64,XEventMetadata>)
        pname = ""
        metas = {}
        lines = []
        for f2, w2, v in _fields(plane):
            if f2 == 2 and w2 == 2:
                pname = v.decode("utf-8", "replace")
            elif f2 == 3 and w2 == 2:
                lines.append(v)
            elif f2 == 4 and w2 == 2:
                # map entry: key=1 varint, value=2 XEventMetadata{id=1,name=2}
                k = None
                mname = ""
                for f3, w3, v3 in _fields(v):
                    if f3 == 1 and w3 == 0:
                        k = v3
                    elif f3 == 2 and w3 == 2:
                        for f4, w4, v4 in _fields(v3):
                            if f4 == 2 and w4 == 2:
                                mname = v4.decode("utf-8", "replace")
                if k is not None:
                    metas[k] = mname
        if pname in ("/host:metadata", "Task Environment"):
            continue
        # A device plane carries several OVERLAPPING lines (XLA Modules,
        # XLA Ops, Steps) spanning the same wall time — summing all of
        # them double/triple-counts. Prefer the per-op line when present.
        named = []
        for line in lines:
            lname = ""
            for f3, w3, v3 in _fields(line):
                if f3 == 2 and w3 == 2:
                    lname = v3.decode("utf-8", "replace")
            named.append((lname, line))
        op_lines = [l for n, l in named if "xla ops" in n.lower()]
        use = op_lines or [l for _, l in named]
        totals, op_totals = per_plane.setdefault(pname, ({}, {}))
        for line in use:
            # XLine: events = 4
            for f3, w3, ev in _fields(line):
                if f3 != 4 or w3 != 2:
                    continue
                # XEvent: metadata_id=1, duration_ps=3
                mid = dur = 0
                for f4, w4, v4 in _fields(ev):
                    if f4 == 1 and w4 == 0:
                        mid = v4
                    elif f4 == 3 and w4 == 0:
                        dur = v4
                name = metas.get(mid, f"op_{mid}")
                cat = _categorize(name)
                totals[cat] = totals.get(cat, 0) + dur
                op_totals[name] = op_totals.get(name, 0) + dur
    device_planes = [p for p in per_plane if "TPU" in p or "/device" in p.lower()]
    show = device_planes or list(per_plane)
    for pname in show:
        totals, op_totals = per_plane[pname]
        tot = sum(totals.values()) or 1
        emit({"phase": "categories", "plane": pname,
              "total_ms": round(tot / 1e9, 2),
              "per_step_ms": round(tot / 1e9 / max(steps, 1), 2),
              **{k: round(v / tot, 4)
                 for k, v in sorted(totals.items(),
                                    key=lambda kv: -kv[1])}})
        top = sorted(op_totals.items(), key=lambda kv: -kv[1])[:15]
        for name, dur in top:
            emit({"phase": "top_op", "plane": pname, "name": name[:120],
                  "ms": round(dur / 1e9, 2), "frac": round(dur / tot, 4)})


if __name__ == "__main__":
    sys.exit(main())
