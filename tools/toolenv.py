"""Shared CPU-run preamble for the repo's standalone tools.

Every bench/report script used to copy-paste the same block: put the
repo root on ``sys.path``, pin ``JAX_PLATFORMS=cpu`` before jax import,
drop the ambient TPU-tunnel PJRT plugin from the factory registry (its
backend discovery can hang when the tunnel is down), and keep ``tpu`` a
KNOWN platform name so pallas/checkify lowering registration validates.
This module is the one copy (same trick as tests/conftest.py).

Usage, FIRST thing in a tool (the script's own directory is on
``sys.path`` when run as ``python tools/<name>.py``)::

    import toolenv
    toolenv.force_cpu()            # or force_cpu(devices=8)
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def repo_root() -> str:
    return REPO


def force_cpu(devices: int = 0) -> None:
    """Pin this process to the CPU backend (``devices`` > 0 additionally
    forces an N-device simulated host platform) and scrub non-CPU PJRT
    factories. Idempotent; safe whether or not jax was imported yet."""
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if devices:
        xla_flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in xla_flags:
            os.environ["XLA_FLAGS"] = (
                xla_flags
                + f" --xla_force_host_platform_device_count={devices}"
            ).strip()
    # the axon tunnel plugin must not hijack (or hang) a CPU run
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ.pop("PALLAS_AXON_REMOTE_COMPILE", None)
    import jax
    try:
        from jax._src import xla_bridge as _xb
        for _name in list(_xb._backend_factories):
            if _name != "cpu":
                _xb._backend_factories.pop(_name, None)
        _xb._platform_aliases.setdefault("tpu", "tpu")
    except Exception:
        pass
    # the ambient env may have imported jax already with a TPU platform
    # pinned — override the live config, not just the env
    jax.config.update("jax_platforms", "cpu")
