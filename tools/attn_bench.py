"""On-chip flash-vs-dense attention microbench (fwd+bwd)."""
import time, functools, json, sys
import numpy as np
import jax, jax.numpy as jnp

from paddle_tpu.kernels.flash_attention import flash_attention_bshd

def dense_bshd(q, k, v):
    qt, kt, vt = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
    s = jnp.einsum("bhsd,bhtd->bhst", qt, kt) / np.sqrt(q.shape[-1])
    causal = jnp.tril(jnp.ones(s.shape[-2:], bool))
    s = jnp.where(causal, s, -1e30)
    p = jax.nn.softmax(s.astype(jnp.float32), -1).astype(q.dtype)
    return jnp.swapaxes(jnp.einsum("bhst,bhtd->bhsd", p, vt), 1, 2)

def bench(fn, *args):
    # NB: jax.block_until_ready does not reliably block through the axon
    # tunnel — time a jitted scalar and float() it (host transfer syncs).
    # Sum ALL of dq/dk/dv: summing only dq lets XLA DCE prune the dk/dv
    # backward kernels and understate the backward cost.
    loss = lambda *a: fn(*a).astype(jnp.float32).sum()
    g = jax.jit(lambda *a: sum(t.astype(jnp.float32).sum()
                               for t in jax.grad(loss, argnums=(0, 1, 2))(*a)))
    float(g(*args))
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        float(g(*args))
        ts.append(time.perf_counter() - t0)
    return sorted(ts)[2]

def dense_gqa_bshd(q, k, v):
    rep = q.shape[2] // k.shape[2]
    return dense_bshd(q, jnp.repeat(k, rep, axis=2),
                      jnp.repeat(v, rep, axis=2))

rng = np.random.default_rng(0)
tf_4096 = None
for s in (1024, 2048, 4096, 8192):
    b = max(1, 8192 // s)
    h, d = 16, 64
    q, k, v = (jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
               for _ in range(3))
    tf = bench(functools.partial(flash_attention_bshd, causal=True), q, k, v)
    if s == 4096:
        tf_4096 = tf
    rec = {"seq": s, "batch": b, "flash_ms": round(tf*1e3, 2),
           "backend": jax.default_backend()}
    if s <= 4096:
        # dense fwd+bwd at 8k needs ~9 GB of (B,H,S,S) f32 transients —
        # an OOM risk on a 16 GB chip; at 8k flash stands alone
        td = bench(dense_bshd, q, k, v)
        rec.update(dense_ms=round(td*1e3, 2), speedup=round(td/tf, 2))
    print(json.dumps(rec), flush=True)

# Block-size sweep at the north-star shape (seq 4096): the winner is
# banked in the artifact; apply it with FLAGS_flash_block_q/_k (the
# kernel reads the flags when block sizes aren't passed explicitly)
s, b, h, d = 4096, 2, 16, 64
q, k, v = (jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
           for _ in range(3))
# the main loop's s=4096 record used the FLAG-resolved blocks (not
# necessarily 128/128 if a tuning is already applied) — seed the sweep
# with it under its TRUE label and skip re-measuring that combo
from paddle_tpu.flags import get_flag
seed_bq, seed_bk = int(get_flag("flash_block_q")), int(get_flag("flash_block_k"))
best = (tf_4096, seed_bq, seed_bk) if tf_4096 is not None else None
for bq, bk in ((128, 128), (128, 256), (256, 128), (256, 256),
               (128, 512), (512, 128), (512, 512)):
    if best is not None and (bq, bk) == (seed_bq, seed_bk):
        continue
    try:
        t = bench(functools.partial(flash_attention_bshd, causal=True,
                                    block_q=bq, block_k=bk), q, k, v)
    except Exception as e:                 # a combo may not fit VMEM
        print(json.dumps({"sweep_block_q": bq, "sweep_block_k": bk,
                          "error": repr(e)[:160],
                          "backend": jax.default_backend()}), flush=True)
        continue
    print(json.dumps({"sweep_block_q": bq, "sweep_block_k": bk,
                      "seq": s, "flash_ms": round(t*1e3, 2),
                      "backend": jax.default_backend()}), flush=True)
    if best is None or t < best[0]:
        best = (t, bq, bk)
if best is not None:
    print(json.dumps({"best_block_q": best[1], "best_block_k": best[2],
                      "flash_ms": round(best[0]*1e3, 2), "seq": s,
                      "backend": jax.default_backend()}), flush=True)

# GQA (the 70B north-star layout: rep=8): unexpanded-kv kernel vs
# repeat_interleave + dense
for s in (2048, 4096):
    b, h, hkv, d = max(1, 8192 // s), 16, 2, 64
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
    k, v = (jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.bfloat16)
            for _ in range(2))
    tf = bench(functools.partial(flash_attention_bshd, causal=True), q, k, v)
    td = bench(dense_gqa_bshd, q, k, v)
    print(json.dumps({"seq": s, "batch": b, "gqa_rep": h // hkv,
                      "flash_gqa_ms": round(tf*1e3, 2),
                      "dense_expand_ms": round(td*1e3, 2),
                      "speedup": round(td/tf, 2),
                      "backend": jax.default_backend()}), flush=True)
