"""Render / exercise paddle_tpu runtime telemetry.

Three modes:

  1. **File mode** (default): read a metrics snapshot JSON — either a
     raw ``observability.snapshot()`` dump or any ``BENCH_*.json``-style
     artifact that embeds one under a ``"telemetry"`` key (top-level or
     inside a ``"results"`` row) — and render it as a human table,
     ``--json``, or ``--prom`` (Prometheus text exposition format).
     Histograms get derived p50/p90/p99 columns. ``--memory`` renders
     the memwatch view instead: the per-program CompiledMemoryStats
     table, the KV pool ledger gauges, and device/host watermarks.

         python tools/telemetry_dump.py FUSED_DECODE_BENCH_r06.json
         python tools/telemetry_dump.py snap.json --prom

  2. **Demo mode** (``--demo``): run a small in-process ServingEngine
     load (tiny Llama, CPU-safe), then print the live snapshot and
     optionally write the Chrome-trace timeline (``--trace out.json``;
     open in chrome://tracing or Perfetto). The zero->aha path for the
     telemetry subsystem.

  3. **Overhead mode** (``--demo --overhead``): the same load twice —
     FLAGS_telemetry on vs off — reporting the steady-state decode
     step-time delta (acceptance bar: < 2% on CPU).

No file argument and no --demo reads a snapshot JSON from stdin.
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

QUANTILES = (0.5, 0.9, 0.99)


def extract_snapshot(doc: dict):
    """A snapshot dict from any of the accepted shapes."""
    if "metrics" in doc and isinstance(doc["metrics"], dict):
        return doc
    if isinstance(doc.get("telemetry"), dict):
        return doc["telemetry"]
    for row in doc.get("results", []):
        if isinstance(row, dict) and isinstance(row.get("telemetry"), dict):
            return row["telemetry"]
    raise SystemExit("no metrics snapshot found (expected a "
                     "snapshot dict or an artifact with a 'telemetry' key)")


def extract_memory(doc: dict):
    """An artifact's ``"memory"`` section from any of the accepted
    shapes (same contract as extract_snapshot: top-level or inside a
    ``"results"`` row), or None."""
    if not isinstance(doc, dict):
        return None
    if isinstance(doc.get("memory"), dict):
        return doc["memory"]
    for row in doc.get("results", []):
        if isinstance(row, dict) and isinstance(row.get("memory"), dict):
            return row["memory"]
    return None


def render_memory(snap: dict, doc: dict = None) -> str:
    """The --memory view: per-program compiled-memory table (pivoted
    from the program_memory_bytes gauges, or an artifact's explicit
    "memory" section) + the KV pool ledger + device/host watermarks."""
    lines = []
    mets = snap.get("metrics", {})
    mem = extract_memory(doc) if doc else None
    # ---- per-program table: prefer an artifact's banked rows, else
    # pivot the gauge series back into rows
    rows = []
    if mem:
        rows = mem.get("programs", [])
    if not rows:
        by_key = {}
        fam = mets.get("program_memory_bytes", {"series": []})
        for s in fam["series"]:
            lbl = s["labels"]
            key = (lbl.get("model", ""), lbl["kind"], lbl["bucket"],
                   lbl.get("extra", ""))
            row = by_key.setdefault(key, {
                "model": key[0], "kind": key[1], "bucket": key[2],
                "extra": key[3]})
            row[lbl["section"]] = int(s["value"])
        rows = [by_key[k] for k in sorted(by_key)]
    if rows:
        from paddle_tpu.observability.memory import format_program_table

        lines.append("# program memory (CompiledMemoryStats, bytes)")
        lines.append(format_program_table(rows))
    else:
        lines.append("# no program memory rows (FLAGS_memwatch off, or "
                     "nothing compiled)")
    # ---- pool ledger gauges
    led = []
    for name in ("kv_pool_pages", "kv_pool_bytes"):
        for s in mets.get(name, {"series": []})["series"]:
            lbl = ",".join(f"{k}={v}" for k, v in
                           sorted(s["labels"].items()))
            led.append(f"  {name}{{{lbl}}} = {s['value']:g}")
    for name in ("kv_pool_fragmentation", "serving_kv_pages_in_use",
                 "serving_prefix_pinned_pages",
                 "kv_host_tier_peak_pages"):
        for s in mets.get(name, {"series": []})["series"]:
            lbl = ",".join(f"{k}={v}" for k, v in
                           sorted(s["labels"].items()))
            suffix = f"{{{lbl}}}" if lbl else ""
            led.append(f"  {name}{suffix} = {s['value']:g}")
    if led:
        lines.append("# kv pool ledger")
        lines.extend(led)
    # ---- watermarks: live gauges when present; banked artifacts carry
    # them under memory.watermarks instead (benches snapshot telemetry
    # BEFORE obs.memory.section() publishes the gauges)
    wm = []
    for name in ("device_memory_bytes", "host_memory_bytes"):
        for s in mets.get(name, {"series": []})["series"]:
            lbl = ",".join(f"{k}={v}" for k, v in
                           sorted(s["labels"].items()))
            wm.append(f"  {name}{{{lbl}}} = {s['value']:g}")
    if not wm and mem and isinstance(mem.get("watermarks"), dict):
        banked_wm = mem["watermarks"]
        for dev, stats in sorted(banked_wm.get("devices", {}).items()):
            for k, v in sorted(stats.items()):
                wm.append(f"  device_memory_bytes{{device={dev},"
                          f"stat={k}}} = {v:g}")
        for k, v in sorted(banked_wm.get("host", {}).items()):
            wm.append(f"  host_memory_bytes{{stat={k}}} = {v:g}")
    if wm:
        lines.append("# watermarks")
        lines.extend(wm)
    return "\n".join(lines)


def render_programs() -> str:
    """The --programs view: a LIVE census of the in-process decode
    program cache — one row per cached key (kind, model-signature
    prefix, batch bucket, page budget, dtype, the extra tuple, trace
    count, banked compile seconds) plus the memwatch peak bytes when
    the program's memory row was captured. The cache is process state,
    not a snapshot artifact, so this only shows anything under --demo
    (or when imported by an in-process serving harness)."""
    from paddle_tpu.generation.program_cache import decode_program_cache
    from paddle_tpu.observability.memory import _extra_str, program_table

    cache = decode_program_cache()
    stats = cache.stats()
    keys = cache.keys()                  # admission order
    for k in stats["traces"]:            # traced keys survive a clear of
        if k not in keys:                # _programs only via stats; show
            keys.append(k)               # them too rather than lose them
    mem_peak = {(r["kind"], str(r["bucket"]), str(r["extra"])): r["peak"]
                for r in program_table() if "peak" in r}
    cols = ("kind", "model", "bucket", "pages", "dtype", "extra",
            "traces", "compile_s", "peak_bytes")
    lines = [f"# decode program cache: {stats['programs']} program(s), "
             f"{stats['hits']} hit(s), {stats['misses']} miss(es)"]
    lines.append("  ".join(f"{h:>18s}" for h in cols))
    for k in keys:
        row = (k.kind, k.model_sig[:8], str(k.batch_bucket),
               _extra_str(k.page_budget), k.dtype,
               _extra_str(k.extra) or "-",
               str(stats["traces"].get(k, 0)),
               f"{stats['compile_seconds'].get(k, 0.0):.3f}",
               str(mem_peak.get((k.kind, str(k.batch_bucket),
                                 _extra_str(k.extra)), "-")))
        lines.append("  ".join(f"{v:>18s}" for v in row))
    if not keys:
        lines.append("  (no cached programs in this process)")
    return "\n".join(lines)


def render_table(snap: dict) -> str:
    from paddle_tpu.observability import series_quantile

    lines = []
    for name in sorted(snap.get("metrics", {})):
        fam = snap["metrics"][name]
        for s in fam["series"]:
            lbl = ",".join(f"{k}={v}" for k, v in
                           sorted(s.get("labels", {}).items()))
            tag = f"{name}{{{lbl}}}" if lbl else name
            if fam["type"] == "histogram":
                qs = "  ".join(
                    f"p{int(q * 100)}={series_quantile(s, q):.6g}"
                    if s["count"] else f"p{int(q * 100)}=-"
                    for q in QUANTILES)
                lines.append(f"{tag:52s} {fam['type']:9s} "
                             f"count={s['count']} sum={s['sum']:.6g}  {qs}")
            else:
                lines.append(f"{tag:52s} {fam['type']:9s} "
                             f"value={s['value']:g}")
    return "\n".join(lines)


def run_demo(n_requests: int, tokens: int, trace_path, overhead: bool,
             programs: bool = False):
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import flags, observability as obs
    from paddle_tpu.generation.program_cache import (
        clear_decode_program_cache, decode_program_cache)
    from paddle_tpu.generation.serving import ServingEngine
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    paddle.seed(0)
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, (8 + (i % 3) * 4,))
               .astype(np.int32) for i in range(n_requests)]

    import time

    max_seq = 32 + tokens               # prompts are <= 16 tokens

    def mixed_load():
        """The snapshot/timeline workload: staggered lengths + prefix
        cache, telemetry on."""
        flags.set_flags({"telemetry": True, "memwatch": True})
        clear_decode_program_cache()     # rebind cache telemetry+memwatch
        eng = ServingEngine(model, max_batch=4, page_size=8,
                            max_seq_len=max_seq, prefix_cache=True)
        for p in prompts:
            eng.submit(p, tokens)
        eng.run()
        return decode_program_cache().trace_count(eng.decode_key) - 1

    def interleaved_drain(eng, arms, out, phase):
        """One steady-state drain, alternating the telemetry binding
        per STEP: both arms sample identical machine conditions, which
        is the only way ~µs instrument writes resolve against tens-of-
        µs shared-CPU step noise. ``phase`` rotates which arm takes the
        even steps across drains."""
        for _ in range(4):
            eng.submit(prompts[0], tokens)
        eng.step()                       # prefill step (untimed)
        i = phase
        while eng.has_work():
            which = i % 2
            eng._m = arms[which]
            t0 = time.perf_counter()
            eng.step()
            out[which].append((time.perf_counter() - t0) * 1e3)
            i += 1

    prior = flags.snapshot(("telemetry", "memwatch")).as_tuple()
    try:
        retraces = mixed_load()
        snap = obs.registry().snapshot()
        if trace_path:
            obs.tracer().save(trace_path)
            print(f"chrome trace -> {trace_path} "
                  f"({len(obs.tracer())} events)", file=sys.stderr)
        result = {"steady_retraces": retraces}
        if overhead:
            # ONE engine, ONE compiled executable, telemetry binding
            # alternated per STEP. Two confounders force this design:
            # separate engines compile separate executables whose
            # memory layouts alone differ by more per step than the
            # instrument writes being measured, and shared-CPU drift is
            # tens of µs over a window — per-step alternation under
            # identical process conditions is the estimator that
            # resolves single-digit-µs telemetry cost. p10 of each
            # arm's distribution is compared (min is a single fragile
            # sample; the median still carries scheduler tail noise).
            from paddle_tpu.generation.serving import _NullEngineTelemetry

            flags.set_flags({"telemetry": True})
            clear_decode_program_cache()
            eng = ServingEngine(model, max_batch=4, page_size=8,
                                max_seq_len=max_seq)
            for _ in range(4):
                eng.submit(prompts[0], 4)
            eng.run()                    # compile prefill+decode (untimed)
            real_m = eng._m
            arms = {0: real_m, 1: _NullEngineTelemetry()}
            out = {0: [], 1: []}
            for r in range(8):
                interleaved_drain(eng, arms, out, phase=r)
            eng._m = real_m
            on_s, off_s = sorted(out[0]), sorted(out[1])
            on = on_s[len(on_s) // 10]
            off = off_s[len(off_s) // 10]
            result.update(
                step_ms_on=round(on, 3), step_ms_off=round(off, 3),
                overhead_pct=(round((on - off) / off * 100, 2)
                              if off else None))
        print(json.dumps(result), file=sys.stderr)
        # the census reads LIVE cache state, so render it before the
        # finally clears the cache (the snapshot survives, keys don't)
        prog_text = render_programs() if programs else None
    finally:
        flags.set_flags(dict(prior))
        clear_decode_program_cache()
    return snap, prog_text


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", nargs="?", help="snapshot or artifact JSON "
                    "(stdin when omitted)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the snapshot as JSON")
    ap.add_argument("--prom", action="store_true",
                    help="emit Prometheus text exposition format")
    ap.add_argument("--memory", action="store_true",
                    help="memwatch view: per-program compiled-memory "
                    "table + KV pool ledger + watermarks")
    ap.add_argument("--programs", action="store_true",
                    help="live decode-program-cache census: one row per "
                    "cached DecodeKey (kind/model/bucket/pages/dtype/"
                    "extra) with trace counts, compile seconds, and "
                    "memwatch peak bytes; pairs with --demo")
    ap.add_argument("--demo", action="store_true",
                    help="run a tiny in-process ServingEngine load and "
                    "dump ITS telemetry")
    ap.add_argument("--overhead", action="store_true",
                    help="with --demo: A/B telemetry on vs off step time")
    ap.add_argument("--trace", metavar="PATH",
                    help="with --demo: write the Chrome-trace timeline")
    ap.add_argument("--requests", type=int, default=8)
    ap.add_argument("--tokens", type=int, default=16)
    args = ap.parse_args()

    doc = None
    prog_text = None
    if args.demo:
        snap, prog_text = run_demo(args.requests, args.tokens, args.trace,
                                   args.overhead, programs=args.programs)
    else:
        if args.programs:
            # live cache of THIS process — no demo means nothing was
            # admitted, but the empty census (with its explanatory
            # trailer line) is still the honest answer
            print(render_programs())
            return 0
        if args.path:
            with open(args.path) as fh:
                doc = json.load(fh)
        else:
            doc = json.load(sys.stdin)
        snap = extract_snapshot(doc)

    if args.prom:
        from paddle_tpu.observability import to_prometheus
        sys.stdout.write(to_prometheus(snap))
    elif args.as_json:
        json.dump(snap, sys.stdout, indent=1)
        sys.stdout.write("\n")
    elif args.memory:
        print(render_memory(snap, doc))
    elif args.programs:
        print(prog_text)
    else:
        print(render_table(snap))
    return 0


if __name__ == "__main__":
    sys.exit(main())
