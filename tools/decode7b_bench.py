"""Llama-2-7B weight-only-int8 serving on ONE v5e chip.

BASELINE configs[1] names Llama-2 7B as the v5e model; its bf16 weights
(13.4 GB) cannot even materialize next to an int8 copy on a 16 GB chip.
This bench exists because the framework's streaming quantization path
(nn/quant.py QuantizedLinear.from_linear over LazyGuard meta params)
makes the model loadable at all: Linears materialize one at a time,
quantize to int8 on device, and free their bf16 — peak HBM is the int8
weights accumulated so far plus one dense layer (~90 MB).

Measures the serving path end to end on the ambient backend:
  1. build+quantize wall time and resulting weight bytes;
  2. paged-KV greedy decode (kernels/paged_attention.py block tables —
     the block_multihead_attention serving machinery) at batch 1 and 8;
  3. the int8 HBM roofline these numbers chase: a single decode token
     must stream every int8 weight byte once, so tokens/sec tops out
     near bandwidth / weight_bytes (~819 GB/s / 6.6 GB ~ 124 tok/s
     single-stream on v5e; batching amortizes the same bytes).

Timing follows bench.py's decode protocol: warm compile first, host-pull
sync every run (block_until_ready is unreliable through the axon
tunnel), steady-state rate = (N-1) tokens / (t_full - t_prefill_plus_1).

Test mode (CHIP_SPRINT_TEST=1): LlamaConfig.tiny() on CPU validates the
full plumbing — lazy build, quantize, paged decode, JSON schema.
"""
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_BACKEND = "unknown"


def emit(d: dict) -> None:
    d.setdefault("backend", _BACKEND)
    print(json.dumps(d), flush=True)


def main() -> int:
    import numpy as np

    import jax

    import paddle_tpu as paddle
    from paddle_tpu.framework import materialize
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.nn.quant import quantize_linears

    global _BACKEND
    _BACKEND = jax.default_backend()
    test_mode = os.environ.get("CHIP_SPRINT_TEST") == "1"
    cfg = LlamaConfig.tiny() if test_mode else LlamaConfig.llama2_7b()
    decode_tokens = 8 if test_mode else int(
        os.environ.get("BENCH_DECODE_TOKENS", "128"))
    prompt_len = 8 if test_mode else 128
    page_size = 8 if test_mode else 64

    emit({"phase": "init", "model": "llama2_7b" if not test_mode
          else "llama_tiny", "devices": [str(d) for d in jax.devices()]})

    t0 = time.perf_counter()
    paddle.seed(0)
    with paddle.LazyGuard():
        model = LlamaForCausalLM(cfg)
    model.to(dtype="bfloat16")
    quantize_linears(model)   # streams each Linear: bf16 -> int8 -> free
    materialize(model)        # embeddings + norms (bf16, kept dense)
    model.eval()

    def nbytes(t):
        v = t._value
        return v.size * v.dtype.itemsize

    int8_bytes = sum(nbytes(b) for bname, b in model.named_buffers()
                     if "quant_weight" in bname or "weight_scale" in bname)
    dense_bytes = sum(nbytes(p) for p in model.parameters())
    # sync on the LAST-dispatched buffer (lm_head's int8 weight): device
    # ops complete in dispatch order, so this waits for the whole
    # streamed quantize, not just the first materialized array
    from paddle_tpu.nn.quant import QuantizedLinear
    last_q = [l for l in model.sublayers()
              if isinstance(l, QuantizedLinear)][-1]
    np.asarray(last_q.quant_weight._value[:1, :1])
    emit({"phase": "build_quantize", "s": round(time.perf_counter() - t0, 2),
          "int8_weight_gb": round(int8_bytes / 2**30, 3),
          "dense_param_gb": round(dense_bytes / 2**30, 3)})

    from paddle_tpu.flags import is_tpu_backend
    bw = 819e9 if is_tpu_backend() else 50e9
    roofline = bw / (int8_bytes + dense_bytes)
    emit({"phase": "roofline", "hbm_gb_per_s": bw / 1e9,
          "single_stream_tokens_per_sec_ceiling": round(roofline, 1)})

    rng = np.random.default_rng(0)

    def timed_paged(batch, n_tokens, repeats=2):
        prompt = paddle.to_tensor(rng.integers(
            0, cfg.vocab_size, (batch, prompt_len)).astype(np.int32))

        def run(n):
            out = model.generate_paged(prompt, max_new_tokens=n,
                                       page_size=page_size)
            np.asarray(out.value)      # host-pull sync (tunnel-safe)

        run(n_tokens)                  # warm: compile prefill + decode step
        best = float("inf")
        for _ in range(repeats):
            t = time.perf_counter()
            run(n_tokens)
            best = min(best, time.perf_counter() - t)
        run(1)
        t = time.perf_counter()
        run(1)
        t_one = time.perf_counter() - t
        dt = best - t_one
        steady = (n_tokens - 1) * batch / dt if dt > 0.05 * best else None
        return {"batch": batch, "new_tokens": n_tokens,
                "e2e_s": round(best, 3),
                "prefill_plus_1_s": round(t_one, 3),
                "paged_decode_tokens_per_sec":
                    round(steady, 1) if steady else None}

    for batch in (1, 8):
        rec = timed_paged(batch, decode_tokens)
        rec["phase"] = "paged_decode"
        emit(rec)

    return 0


if __name__ == "__main__":
    sys.exit(main())
