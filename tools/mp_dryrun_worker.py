"""Multi-process mesh dryrun worker (VERDICT r4 item 5).

Launched as N OS processes by ``__graft_entry__.dryrun_multichip`` (or
the fleet launcher) with the launcher's env protocol
(``PADDLE_TRAINER_ID`` / ``PADDLE_TRAINERS_NUM`` /
``PADDLE_MASTER_ENDPOINT``). Proves the cross-process story end to end:

1. rendezvous through the launcher's HTTP KV master — rank 0 publishes
   the jax coordinator address, everyone fetches it;
2. ``jax.distributed.initialize`` forms the global runtime (2 processes
   x 4 local CPU devices = one 8-device mesh);
3. a jitted computation over a ``Mesh`` spanning BOTH processes runs a
   real cross-process collective (the mean over the dp axis), checked
   numerically against the global batch;
4. the fleet topology (HybridCommunicateGroup) builds over the global
   device list.

Reference analogue: multi-node NCCL ProcessGroup init through TCPStore +
an allreduce smoke (test_collective_* multi-node tests).
"""

import json
import os
import socket
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def launch(n_procs: int = 2, devices_per_proc: int = 4,
           timeout: float = 420.0):
    """Shared launcher (used by __graft_entry__.dryrun_multichip AND
    tests/test_multiprocess_mesh.py — one env protocol, one cleanup
    path): start the KV master, spawn ``n_procs`` workers with the
    launcher env protocol, and return their parsed JSON results. Any
    failure kills EVERY worker before raising — a dead rank otherwise
    leaves its peer orphaned inside jax.distributed.initialize."""
    import subprocess

    from paddle_tpu.distributed.launch.kv_master import KVServer

    srv = KVServer(host="127.0.0.1").start()
    procs = []
    try:
        for r in range(n_procs):
            env = dict(os.environ)
            env.pop("PALLAS_AXON_POOL_IPS", None)
            env.pop("PALLAS_AXON_REMOTE_COMPILE", None)
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = ("--xla_force_host_platform_device_count="
                                f"{devices_per_proc}")
            env["PADDLE_TRAINER_ID"] = str(r)
            env["PADDLE_TRAINERS_NUM"] = str(n_procs)
            env["PADDLE_MASTER_ENDPOINT"] = f"127.0.0.1:{srv.port}"
            env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
            procs.append(subprocess.Popen(
                [sys.executable, os.path.abspath(__file__)], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
        outs = []
        for r, p in enumerate(procs):
            so, se = p.communicate(timeout=timeout)
            if p.returncode != 0:
                raise RuntimeError(f"mp worker {r} rc={p.returncode}: "
                                   f"{se[-1500:]}")
            outs.append(json.loads(so.strip().splitlines()[-1]))
        return outs
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        srv.stop()


def main() -> None:
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    nprocs = int(os.environ["PADDLE_TRAINERS_NUM"])
    master = os.environ["PADDLE_MASTER_ENDPOINT"]

    from paddle_tpu.distributed.launch.kv_master import KVClient
    kv = KVClient(master)
    if rank == 0:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        coord = f"127.0.0.1:{s.getsockname()[1]}"
        s.close()
        kv.put("jax/coordinator", coord.encode())
    else:
        deadline = time.time() + 60
        coord = None
        while time.time() < deadline:
            try:
                got = kv.prefix("jax/").get("jax/coordinator")
            except Exception:
                got = None
            if got:
                coord = got.decode() if isinstance(got, bytes) else got
                break
            time.sleep(0.2)
        assert coord, "rank0 never published the jax coordinator"

    import jax
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=nprocs, process_id=rank)
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    local = jax.local_device_count()
    assert jax.process_count() == nprocs, jax.process_count()
    n_global = jax.device_count()
    assert n_global == nprocs * local, (n_global, nprocs, local)

    # ---- global mesh spanning both processes + a real collective ---------
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    per = 2                                     # rows per device
    rows = n_global * per

    def row(i):
        return np.full((per, 4), float(i), np.float32)

    global_batch = np.concatenate([row(i) for i in range(n_global)])
    arr = jax.make_array_from_callback(
        (rows, 4), sharding,
        lambda idx: global_batch[idx])

    @jax.jit
    def global_mean(x):                          # cross-process all-reduce
        return jnp.mean(x)

    got = float(global_mean(arr))
    want = float(global_batch.mean())
    assert abs(got - want) < 1e-6, (got, want)

    # ---- fleet topology over the global device list ----------------------
    from paddle_tpu.distributed.fleet.base_topology import (
        create_hybrid_communicate_group)
    hcg = create_hybrid_communicate_group(dp_degree=n_global)
    assert hcg.get_data_parallel_world_size() == n_global

    # ---- FULL train step across both processes ---------------------------
    # dp=8 over the 2-process mesh: params replicated globally (identical
    # seed per process), each process feeds its local half of the global
    # batch (per-rank data, like a DistributedBatchSampler shard); the
    # jitted fwd+bwd+AdamW step runs ONE SPMD program over both
    # processes, with the dp grad-sum riding the cross-process
    # collectives verified above. Losses must agree bit-for-bit across
    # ranks (replicated output).
    import paddle_tpu as paddle
    from paddle_tpu.hapi import TrainStep
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=2, max_position_embeddings=32,
                    hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0)
    model = GPTForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters())
    ts = TrainStep(model, opt, mesh=mesh, data_axes=("dp",))
    lrng = np.random.default_rng(100 + rank)      # per-rank data
    local_b = n_global // nprocs                  # rows this process feeds
    losses = []
    for _ in range(3):
        ids = lrng.integers(0, cfg.vocab_size, (local_b, 17))
        x = paddle.to_tensor(ids[:, :-1].astype(np.int32))
        yb = paddle.to_tensor(ids[:, 1:].astype(np.int32))
        losses.append(float(ts(x, yb)))
    assert all(np.isfinite(l) for l in losses), losses

    print(json.dumps({
        "rank": rank, "processes": jax.process_count(),
        "global_devices": n_global, "local_devices": local,
        "collective_mean": got, "expected": want,
        "train_losses": [round(l, 6) for l in losses], "ok": True,
    }), flush=True)


if __name__ == "__main__":
    main()
