"""Multi-process mesh dryrun worker (VERDICT r4 item 5).

Launched as N OS processes by ``__graft_entry__.dryrun_multichip`` (or
the fleet launcher) with the launcher's env protocol
(``PADDLE_TRAINER_ID`` / ``PADDLE_TRAINERS_NUM`` /
``PADDLE_MASTER_ENDPOINT``). Proves the cross-process story end to end:

1. rendezvous through the launcher's HTTP KV master — rank 0 publishes
   the jax coordinator address, everyone fetches it;
2. ``jax.distributed.initialize`` forms the global runtime (2 processes
   x 4 local CPU devices = one 8-device mesh);
3. a jitted computation over a ``Mesh`` spanning BOTH processes runs a
   real cross-process collective (the mean over the dp axis), checked
   numerically against the global batch;
4. the fleet topology (HybridCommunicateGroup) builds over the global
   device list.

Reference analogue: multi-node NCCL ProcessGroup init through TCPStore +
an allreduce smoke (test_collective_* multi-node tests).
"""

import json
import os
import socket
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> None:
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    nprocs = int(os.environ["PADDLE_TRAINERS_NUM"])
    master = os.environ["PADDLE_MASTER_ENDPOINT"]

    from paddle_tpu.distributed.launch.kv_master import KVClient
    kv = KVClient(master)
    if rank == 0:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        coord = f"127.0.0.1:{s.getsockname()[1]}"
        s.close()
        kv.put("jax/coordinator", coord.encode())
    else:
        deadline = time.time() + 60
        coord = None
        while time.time() < deadline:
            try:
                got = kv.prefix("jax/").get("jax/coordinator")
            except Exception:
                got = None
            if got:
                coord = got.decode() if isinstance(got, bytes) else got
                break
            time.sleep(0.2)
        assert coord, "rank0 never published the jax coordinator"

    import jax
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=nprocs, process_id=rank)
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    local = jax.local_device_count()
    assert jax.process_count() == nprocs, jax.process_count()
    n_global = jax.device_count()
    assert n_global == nprocs * local, (n_global, nprocs, local)

    # ---- global mesh spanning both processes + a real collective ---------
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    per = 2                                     # rows per device
    rows = n_global * per

    def row(i):
        return np.full((per, 4), float(i), np.float32)

    global_batch = np.concatenate([row(i) for i in range(n_global)])
    arr = jax.make_array_from_callback(
        (rows, 4), sharding,
        lambda idx: global_batch[idx])

    @jax.jit
    def global_mean(x):                          # cross-process all-reduce
        return jnp.mean(x)

    got = float(global_mean(arr))
    want = float(global_batch.mean())
    assert abs(got - want) < 1e-6, (got, want)

    # ---- fleet topology over the global device list ----------------------
    from paddle_tpu.distributed.fleet.base_topology import (
        create_hybrid_communicate_group)
    hcg = create_hybrid_communicate_group(dp_degree=n_global)
    assert hcg.get_data_parallel_world_size() == n_global

    print(json.dumps({
        "rank": rank, "processes": jax.process_count(),
        "global_devices": n_global, "local_devices": local,
        "collective_mean": got, "expected": want, "ok": True,
    }), flush=True)


if __name__ == "__main__":
    main()
