"""Adversarial expansion of the API-coverage manifest (VERDICT r3 item 7).

Provenance: the reference mount is empty every round, so these names are
curated from the upstream PaddlePaddle 2.6 public API documentation
(api/paddle/Overview + per-module Overview pages) and the upstream
``python/paddle/__init__.py`` ``__all__`` structure as described in
SURVEY.md §2.2 — deliberately INCLUDING areas this rebuild has not
covered (vision model zoo, audio/text datasets, onnx export) so the
reported percentage is honest rather than self-confirming. The special
module key ``"Tensor"`` is resolved against ``paddle_tpu.Tensor``
attributes (upstream: python/paddle/tensor/tensor.prototype.pyi — the
method surface of ``paddle.Tensor``).
"""

# ~340 paddle.Tensor methods/properties (upstream Tensor docs: every
# tensor op surfaces as a method; _ suffix = inplace).
TENSOR_METHODS = """
abs acos acosh add add_ addmm all allclose amax amin angle any argmax
argmin argsort asin asinh astype atan atan2 atanh backward bincount
bitwise_and bitwise_not bitwise_or bitwise_xor bmm broadcast_to
bucketize cast ceil ceil_ cholesky chunk clip clip_ clone concat conj
cos cosh count_nonzero cpu cross cumprod cumsum cummax cummin detach
diag diagonal diff digamma dim dist divide dot dsplit eig eigvals
equal equal_all erf erfinv exp exp_ expand expand_as expm1 fill_
fill_diagonal_ flatten flatten_ flip floor floor_ floor_divide floor_mod
fmax fmin frac gather gather_nd gcd greater_equal greater_than
heaviside histogram hsplit imag increment index_add index_put
index_sample index_select inner inverse isclose isfinite isinf isnan
item kron kthvalue lcm lerp lerp_ less_equal less_than lgamma log
log10 log1p log2 logcumsumexp logical_and logical_not logical_or
logical_xor logit logsumexp lstsq lu masked_fill masked_fill_
masked_select masked_scatter matmul max maximum mean median min minimum
mm mod mode moveaxis multiply multiplex mv nan_to_num nanmean nanmedian
nansum neg nonzero norm normal_ not_equal numel numpy outer pow prod
put_along_axis quantile rad2deg real reciprocal reciprocal_ register_hook
remainder remainder_ repeat_interleave reshape reshape_ roll rot90
round round_ rsqrt rsqrt_ scale scale_ scatter scatter_ scatter_nd
scatter_nd_add searchsorted set_value sgn shard_index sign sin sinh
slice sort split sqrt sqrt_ square squeeze squeeze_ stack
stanh std strided_slice subtract subtract_ sum t take take_along_axis
tanh tanh_ tensor_split tile to tolist topk trace transpose tril triu
trunc unbind uniform_ unique unique_consecutive unsqueeze unsqueeze_
unstack var vsplit where zero_
logaddexp copysign signbit isposinf isneginf polygamma i0 i0e i1 i1e
nanquantile renorm trapezoid unflatten as_strided positive block_diag
vander cumulative_trapezoid ldexp hypot element_size diag_embed
diagonal_scatter index_fill index_fill_ abs_ sin_ cos_ tan_
""".split()

TENSOR_PROPERTIES = """
T dtype grad is_leaf name ndim persistable place shape size
stop_gradient
""".split()

EXTRA = {
    "Tensor": TENSOR_METHODS + TENSOR_PROPERTIES,
    # quantization framework (upstream python/paddle/quantization):
    # added r05 second session along with the implementation
    "quantization": ["QuantConfig", "QAT", "PTQ", "AbsmaxObserver",
                     "FakeQuanterWithAbsMaxObserver"],
    "": [
        # framework / device / dtype infra (upstream top level)
        "Tensor", "dtype", "finfo", "iinfo", "get_default_dtype",
        "set_default_dtype", "set_grad_enabled", "is_grad_enabled",
        "no_grad", "enable_grad", "grad", "disable_static",
        "enable_static", "in_dynamic_mode", "get_flags", "set_flags",
        "save", "load", "summary", "flops", "Model", "LazyGuard",
        "set_printoptions", "einsum", "is_complex", "is_floating_point",
        "is_integer", "crop", "increment", "multiplex", "shard_index",
        "standard_normal", "poisson", "log_normal", "cauchy_",
        "unflatten", "as_strided", "positive", "negative",
        "combinations", "polar", "vander", "trapezoid", "cumulative_trapezoid",
        "logaddexp", "logit", "i0", "i0e", "i1", "i1e", "polygamma",
        "copysign", "signbit", "isposinf", "isneginf", "isreal",
        "index_fill", "index_fill_", "diagonal_scatter", "select_scatter",
        "slice_scatter", "masked_scatter_", "block_diag", "stanh",
        "renorm", "quantile", "nanquantile", "pdist", "cdist",
        "batch", "scale", "clip_", "subtract_", "add_", "numel",
        "nextafter", "frexp", "masked_fill", "masked_fill_",
        "histogram_bin_edges", "bernoulli_", "binomial",
    ],
    "device": [
        "set_device", "get_device", "get_all_device_type",
        "get_all_custom_device_type", "get_available_device",
        "get_available_custom_device", "is_compiled_with_cuda",
        "is_compiled_with_rocm", "is_compiled_with_xpu",
        "is_compiled_with_custom_device", "cuda",
    ],
    "regularizer": ["L1Decay", "L2Decay"],
    "callbacks": [
        "Callback", "EarlyStopping", "LRScheduler", "ModelCheckpoint",
        "ProgBarLogger", "ReduceLROnPlateau", "VisualDL",
    ],
    "nn": [
        # layer-zoo long tail (upstream paddle.nn Overview)
        "Identity", "Flatten", "Unflatten", "UpsamplingBilinear2D",
        "UpsamplingNearest2D", "Upsample", "AlphaDropout", "Dropout2D",
        "Dropout3D", "FeatureAlphaDropout",
        "CELU", "GLU", "Hardshrink", "Hardsigmoid", "Hardswish",
        "Hardtanh", "LeakyReLU", "LogSigmoid", "LogSoftmax", "Maxout",
        "Mish", "PReLU", "RReLU", "ReLU6", "SELU", "Silu", "Softmax2D",
        "Softplus", "Softshrink", "Softsign", "Swish", "Tanhshrink",
        "ThresholdedReLU",
        "Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
        "Conv3DTranspose",
        "AvgPool1D", "AvgPool2D", "AvgPool3D", "MaxPool1D", "MaxPool2D",
        "MaxPool3D", "AdaptiveAvgPool1D", "AdaptiveAvgPool2D",
        "AdaptiveAvgPool3D", "AdaptiveMaxPool1D", "AdaptiveMaxPool2D",
        "AdaptiveMaxPool3D", "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D",
        "FractionalMaxPool2D", "FractionalMaxPool3D",
        "BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
        "SyncBatchNorm", "GroupNorm", "InstanceNorm1D", "InstanceNorm2D",
        "InstanceNorm3D", "LayerNorm", "LocalResponseNorm", "RMSNorm",
        "SpectralNorm",
        "Pad1D", "Pad2D", "Pad3D", "ZeroPad1D", "ZeroPad2D", "ZeroPad3D",
        "CosineSimilarity", "PairwiseDistance",
        "Embedding", "Linear", "Bilinear", "Dropout",
        "SimpleRNN", "LSTM", "GRU", "SimpleRNNCell", "LSTMCell", "GRUCell",
        "RNN", "BiRNN", "RNNCellBase",
        "AdaptiveLogSoftmaxWithLoss",
        "MultiHeadAttention", "Transformer", "TransformerDecoder",
        "TransformerDecoderLayer", "TransformerEncoder",
        "TransformerEncoderLayer",
        "BCELoss", "BCEWithLogitsLoss", "CrossEntropyLoss", "CTCLoss",
        "CosineEmbeddingLoss", "GaussianNLLLoss", "HSigmoidLoss",
        "HingeEmbeddingLoss", "KLDivLoss", "L1Loss", "MarginRankingLoss",
        "MSELoss", "MultiLabelSoftMarginLoss", "MultiMarginLoss",
        "NLLLoss", "PoissonNLLLoss", "RNNTLoss", "SmoothL1Loss",
        "SoftMarginLoss", "TripletMarginLoss",
        "TripletMarginWithDistanceLoss",
        "PixelShuffle", "PixelUnshuffle", "ChannelShuffle", "Fold",
        "Unfold",
        "Layer", "LayerList", "LayerDict", "Sequential", "ParameterList",
        "ClipGradByGlobalNorm", "ClipGradByNorm", "ClipGradByValue",
        "initializer", "utils",
    ],
    "nn.initializer": [
        "Assign", "Bilinear", "Constant", "Dirac", "KaimingNormal",
        "KaimingUniform", "Normal", "Orthogonal", "TruncatedNormal",
        "Uniform", "XavierNormal", "XavierUniform", "calculate_gain",
        "set_global_initializer",
    ],
    "nn.utils": [
        "clip_grad_norm_", "clip_grad_value_", "parameters_to_vector",
        "remove_weight_norm", "spectral_norm", "vector_to_parameters",
        "weight_norm",
    ],
    "nn.functional": [
        # functional long tail
        "adaptive_log_softmax_with_loss", 
        "celu", "glu", "gumbel_softmax", "hardshrink", "hardsigmoid",
        "hardswish", "hardtanh", "leaky_relu", "log_sigmoid",
        "log_softmax", "maxout", "mish", "prelu", "rrelu", "relu6",
        "selu", "silu", "softmax_", "softplus", "softshrink", "softsign",
        "swish", "tanhshrink", "thresholded_relu",
        "alpha_dropout", "dropout2d", "dropout3d", "feature_alpha_dropout",
        "fold", "unfold", "pixel_shuffle", "pixel_unshuffle",
        "channel_shuffle", "interpolate", "upsample", "grid_sample",
        "affine_grid", "pad", "zeropad2d",
        "avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d",
        "max_pool2d", "max_pool3d", "adaptive_avg_pool1d",
        "adaptive_avg_pool2d", "adaptive_avg_pool3d",
        "adaptive_max_pool1d", "adaptive_max_pool2d", "adaptive_max_pool3d",
        "max_unpool1d", "max_unpool2d", "max_unpool3d",
        "binary_cross_entropy", "binary_cross_entropy_with_logits",
        "cosine_embedding_loss", "cross_entropy", "ctc_loss",
        "gaussian_nll_loss", "hinge_embedding_loss", "hsigmoid_loss",
        "kl_div", "l1_loss", "log_loss", "margin_cross_entropy",
        "margin_ranking_loss", "mse_loss", "multi_label_soft_margin_loss",
        "multi_margin_loss", "nll_loss", "npair_loss", "poisson_nll_loss",
        "rnnt_loss", "sigmoid_focal_loss", "smooth_l1_loss",
        "soft_margin_loss", "softmax_with_cross_entropy", "square_error_cost",
        "triplet_margin_loss", "triplet_margin_with_distance_loss",
        "cosine_similarity", "linear", "bilinear", "embedding",
        "one_hot", "label_smooth", "class_center_sample",
        "scaled_dot_product_attention", "flash_attention",
        "flash_attn_unpadded", "sequence_mask", "normalize",
        "local_response_norm", "batch_norm", "group_norm", "instance_norm",
        "layer_norm", "rms_norm", "temporal_shift",
    ],
    "linalg": [
        "cholesky", "cholesky_solve", "cond", "corrcoef", "cov", "det",
        "eig", "eigh", "eigvals", "eigvalsh", "householder_product",
        "inv", "lstsq", "lu", "lu_unpack", "matrix_exp", "matrix_norm",
        "matrix_power", "matrix_rank", "multi_dot", "norm", "pca_lowrank",
        "pinv", "qr", "slogdet", "solve", "svd", "svd_lowrank",
        "triangular_solve", "vector_norm",
    ],
    "io": [
        "BatchSampler", "ChainDataset", "ComposeDataset", "DataLoader",
        "Dataset", "DistributedBatchSampler", "IterableDataset",
        "RandomSampler", "Sampler", "SequenceSampler", "Subset",
        "SubsetRandomSampler", "TensorDataset", "WeightedRandomSampler",
        "get_worker_info", "random_split",
    ],
    "distributed": [
        "rpc", "get_backend", "is_available",
        "destroy_process_group", "get_group", "gloo_init_parallel_env",
        "stream", "save_state_dict", "load_state_dict",
        "alltoall_single", "reduce_scatter", "is_initialized",
        "launch", "checkpoint",
    ],
    "distributed.communication.stream": [
        "all_gather", "all_reduce", "alltoall", "alltoall_single",
        "broadcast", "reduce", "reduce_scatter", "recv", "scatter",
        "send",
    ],
    "distributed.rpc": [
        "init_rpc", "rpc_sync", "rpc_async", "shutdown",
        "get_worker_info", "get_all_worker_infos", "get_current_worker_info",
    ],
    "static": [
        "Program", "program_guard", "data", "Executor",
        "default_main_program", "default_startup_program", "InputSpec",
        "name_scope", "device_guard", "cpu_places", "cuda_places",
        "global_scope", "scope_guard", "append_backward", "gradients",
        "save", "load", "save_inference_model", "load_inference_model",
        "normalize_program", "Variable",
    ],
    "jit": [
        "to_static", "not_to_static", "save", "load", "ignore_module",
        "enable_to_static", "TranslatedLayer",
    ],
    "amp": [
        "GradScaler", "auto_cast", "decorate", "is_bfloat16_supported",
        "is_float16_supported", "debugging",
    ],
    "incubate": [
        "segment_max", "segment_mean", "segment_min", "segment_sum",
        "softmax_mask_fuse", "softmax_mask_fuse_upper_triangle",
        "identity_loss", "graph_khop_sampler", "graph_reindex",
        "graph_sample_neighbors",
    ],
    "vision": ["get_image_backend", "set_image_backend", "image_load",
               "transforms", "models", "datasets", "ops"],
    "vision.transforms": [
        "BaseTransform", "BrightnessTransform", "CenterCrop",
        "ColorJitter", "Compose", "ContrastTransform", "Grayscale",
        "HueTransform", "Normalize", "Pad", "RandomCrop",
        "RandomErasing", "RandomHorizontalFlip", "RandomResizedCrop",
        "RandomRotation", "RandomVerticalFlip", "Resize",
        "SaturationTransform", "ToTensor", "Transpose", "RandomAffine",
        "RandomPerspective", "affine", "perspective", "erase",
        "adjust_brightness",
        "adjust_contrast", "adjust_hue", "center_crop", "crop", "hflip",
        "normalize", "pad", "resize", "rotate", "to_grayscale",
        "to_tensor", "vflip",
    ],
    "vision.models": [
        "AlexNet", "alexnet", "DenseNet", "densenet121", "densenet161",
        "densenet169", "densenet201", "densenet264", "GoogLeNet",
        "googlenet", "InceptionV3", "inception_v3", "LeNet", "MobileNetV1",
        "mobilenet_v1", "MobileNetV2", "mobilenet_v2", "MobileNetV3Large",
        "MobileNetV3Small", "mobilenet_v3_large", "mobilenet_v3_small",
        "ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
        "resnet152", "resnext50_32x4d", "resnext50_64x4d",
        "resnext101_32x4d", "resnext101_64x4d", "resnext152_32x4d",
        "resnext152_64x4d", "ShuffleNetV2", "shufflenet_v2_x0_25",
        "shufflenet_v2_x0_33", "shufflenet_v2_x0_5", "shufflenet_v2_x1_0",
        "shufflenet_v2_x1_5", "shufflenet_v2_x2_0", "shufflenet_v2_swish",
        "SqueezeNet", "squeezenet1_0", "squeezenet1_1", "VGG", "vgg11",
        "vgg13", "vgg16", "vgg19", "wide_resnet50_2", "wide_resnet101_2",
    ],
    "vision.datasets": ["Cifar10", "Cifar100", "FashionMNIST", "Flowers",
                        "MNIST", "VOC2012", "DatasetFolder", "ImageFolder"],
    "vision.ops": ["DeformConv2D", "PSRoIPool", "RoIAlign", "RoIPool",
                   "box_coder", "deform_conv2d", "distribute_fpn_proposals",
                   "generate_proposals", "nms", "prior_box", "psroi_pool",
                   "roi_align", "roi_pool", "yolo_box", "yolo_loss"],
    "onnx": ["export"],
    "audio": ["backends", "datasets", "features", "functional"],
    "text": ["Imdb", "Imikolov", "Movielens", "UCIHousing", "WMT14",
             "WMT16", "viterbi_decode", "ViterbiDecoder"],
    "utils": ["deprecated", "get_weights_path_from_url", "require_version",
              "run_check", "try_import", "unique_name", "cpp_extension",
              "dlpack"],
    "version": ["cuda", "cudnn", "full_version", "major", "minor"],
    "distributed.fleet": [
        "init", "is_first_worker", "worker_index", "worker_num",
        "is_worker", "worker_endpoints", "server_num", "server_index",
        "server_endpoints", "is_server", "barrier_worker", "init_worker",
        "init_server", "run_server", "stop_worker", "distributed_model",
        "distributed_optimizer", "DistributedStrategy",
        "UserDefinedRoleMaker", "PaddleCloudRoleMaker", "UtilBase",
        "get_hybrid_communicate_group", "HybridCommunicateGroup",
        "meta_parallel", "utils",
    ],
    "distributed.fleet.meta_parallel": [
        "ColumnParallelLinear", "RowParallelLinear",
        "VocabParallelEmbedding", "ParallelCrossEntropy", "PipelineLayer",
        "LayerDesc", "SharedLayerDesc", "TensorParallel",
        "PipelineParallel", "ShardingParallel", "get_rng_state_tracker",
    ],
    "distributed.fleet.utils": [
        "recompute", "LocalFS", "HDFSClient",
    ],
    "distributed.auto_parallel": [
        "ProcessMesh", "shard_tensor", "shard_op", "Engine", "Strategy",
    ],
    "distributed.sharding": [
        "group_sharded_parallel", "save_group_sharded_model",
    ],
    "distributed.utils": [
        "global_scatter", "global_gather",
    ],
    "incubate.nn": [
        "FusedBiasDropoutResidualLayerNorm", "FusedFeedForward",
        "FusedLinear", "FusedMultiHeadAttention", "FusedMultiTransformer",
        "FusedTransformerEncoderLayer",
    ],
    "incubate.nn.functional": [
        "fused_bias_dropout_residual_layer_norm", "fused_dropout_add",
        "fused_ec_moe", "fused_feedforward", "fused_layer_norm",
        "fused_linear", "fused_linear_activation", "fused_matmul_bias",
        "fused_multi_head_attention", "fused_multi_transformer",
        "fused_rms_norm", "fused_rotary_position_embedding",
        "masked_multihead_attention", "swiglu", "variable_length_memory_efficient_attention",
    ],
    "incubate.optimizer": ["LookAhead", "ModelAverage", "LBFGS"],
    "geometric": [
        "send_u_recv", "send_ue_recv", "send_uv", "segment_max",
        "segment_mean", "segment_min", "segment_sum", "sample_neighbors",
        "reindex_graph",
    ],
    "hub": ["help", "list", "load"],
    "device.cuda": [
        "Event", "Stream", "current_stream", "device_count",
        "empty_cache", "get_device_capability", "get_device_name",
        "get_device_properties", "max_memory_allocated",
        "max_memory_reserved", "memory_allocated", "memory_reserved",
        "stream_guard", "synchronize",
    ],
    "profiler": ["RecordEvent", "SortedKeys", "SummaryView",
                 "load_profiler_result"],
    "amp.debugging": [
        "TensorCheckerConfig", "check_numerics",
        "collect_operator_stats", "disable_operator_stats_collection",
        "disable_tensor_checker", "enable_operator_stats_collection",
        "enable_tensor_checker", "compare_accuracy",
    ],
    "utils.cpp_extension": ["CppExtension", "CUDAExtension", "load",
                            "setup", "get_build_directory"],
    "utils.dlpack": ["from_dlpack", "to_dlpack"],
    "utils.unique_name": ["generate", "guard", "switch"],
    "incubate.asp": ["decorate", "prune_model", "set_excluded_layers",
                     "reset_excluded_layers"],
    "incubate.distributed.models.moe": ["MoELayer", "GShardGate",
                                        "SwitchGate", "BaseGate"],
    "distributed.fleet.meta_optimizers": [
        "DygraphShardingOptimizer", "HybridParallelOptimizer",
        "HybridParallelGradScaler",
    ],
    "autograd": [
        "backward", "hessian", "jacobian", "jvp", "vjp", "PyLayer",
        "PyLayerContext", "saved_tensors_hooks", "no_grad", "is_grad_enabled",
        "set_grad_enabled",
    ],
    "fft": [
        "fft", "fft2", "fftn", "ifft", "ifft2", "ifftn", "rfft", "rfft2",
        "rfftn", "irfft", "irfft2", "irfftn", "hfft", "hfft2", "hfftn",
        "ihfft", "ihfft2", "ihfftn", "fftfreq", "rfftfreq", "fftshift",
        "ifftshift",
    ],
    "signal": ["stft", "istft"],
    "optimizer": [
        "Adadelta", "Adagrad", "Adam", "AdamW", "Adamax", "ASGD",
        "LBFGS", "Lamb", "Momentum", "NAdam", "Optimizer", "RAdam",
        "RMSProp", "Rprop", "SGD", "lr",
    ],
    "sparse": [
        "sparse_coo_tensor", "sparse_csr_tensor", "is_same_shape", "nn",
        "add", "subtract", "multiply", "divide", "matmul", "masked_matmul",
        "mv", "transpose", "reshape", "sum", "abs", "sin", "sinh", "tan",
        "tanh", "asin", "asinh", "atan", "atanh", "sqrt", "square",
        "log1p", "expm1", "pow", "neg", "cast", "coalesce", "rad2deg",
        "deg2rad",     ],
    "static.nn": [
        "fc", "batch_norm", "embedding", "conv2d", "conv3d", "cond",
        "while_loop", "case", "switch_case", "py_func", "sequence_expand",
        "prelu", "spectral_norm", "layer_norm", "group_norm", "nce",
    ],
    "metric": ["Accuracy", "Auc", "Metric", "Precision", "Recall",
               "accuracy"],
    "distribution": [
        "AbsTransform", "AffineTransform", "Bernoulli", "Beta",
        "Binomial", "Categorical", "Cauchy", "ChainTransform",
        "ContinuousBernoulli", "Dirichlet", "Distribution",
        "ExpTransform", "Exponential", "ExponentialFamily", "Gamma",
        "Geometric", "Gumbel", "Independent", "IndependentTransform",
        "Laplace", "LogNormal", "Multinomial", "MultivariateNormal",
        "Normal", "Poisson", "PowerTransform", "ReshapeTransform",
        "SigmoidTransform", "SoftmaxTransform", "StackTransform",
        "StickBreakingTransform", "StudentT", "TanhTransform",
        "Transform", "TransformedDistribution", "Uniform",
        "kl_divergence", "register_kl",
    ],
}
