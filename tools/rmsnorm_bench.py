"""On-chip rms_norm Pallas-vs-XLA microbench (fwd+bwd).

Companion to tools/attn_bench.py (VERDICT round-2 item 1c). Emits one JSON
line per (rows, hidden) shape: pallas vs plain-jnp rms_norm median time over
5 runs of a jitted grad step.
"""
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.kernels.rms_norm import rms_norm_pallas


def rms_norm_xla(x, w, eps=1e-6):
    # Must return x.dtype like the pallas kernel does — an f32 output would
    # double the store bytes and skew the comparison.
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    y = x.astype(jnp.float32) * jax.lax.rsqrt(var + eps) * w.astype(jnp.float32)
    return y.astype(x.dtype)


def bench(fn, x, w):
    # float() of a jitted scalar is the reliable host sync through the tunnel.
    # Sum ALL grads into the scalar — returning only gx lets XLA DCE prune
    # the dW computation and understate the backward cost.
    loss = lambda x, w: fn(x, w).astype(jnp.float32).sum()

    def step(x, w):
        gx, gw = jax.grad(loss, argnums=(0, 1))(x, w)
        return gx.astype(jnp.float32).sum() + gw.astype(jnp.float32).sum()

    g = jax.jit(step)
    float(g(x, w))
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        float(g(x, w))
        ts.append(time.perf_counter() - t0)
    return sorted(ts)[2]


def main():
    backend = jax.default_backend()
    rng = np.random.default_rng(0)
    for rows, h in ((8192, 1024), (8192, 4096), (32768, 4096), (8192, 8192)):
        x = jnp.asarray(rng.standard_normal((rows, h)), jnp.bfloat16)
        w = jnp.asarray(rng.standard_normal((h,)), jnp.float32)
        tp = bench(rms_norm_pallas, x, w)
        tx = bench(rms_norm_xla, x, w)
        print(json.dumps({"rows": rows, "hidden": h,
                          "pallas_ms": round(tp * 1e3, 3),
                          "xla_ms": round(tx * 1e3, 3),
                          "speedup": round(tx / tp, 2),
                          "backend": backend}), flush=True)


if __name__ == "__main__":
    main()
