#!/usr/bin/env python
"""Unified static-analysis gate: tracecheck + meshcheck in ONE parse.

Usage:
    python tools/analyze.py                      # both suites, gate
    python tools/analyze.py --suite meshcheck    # one suite
    python tools/analyze.py --json
    python tools/analyze.py --update-baseline    # rewrites BOTH baselines
    python tools/analyze.py --list-rules

The package is parsed ONCE (ast.parse dominates analyzer wall clock);
both suites consume the same ParsedPackage, so the combined tier-1 gate
stays inside the r08 ~15 s budget.  Pure AST — the analysis package is
loaded standalone (never through ``paddle_tpu/__init__``), so no jax
import, no device; safe as a pre-commit hook or bare CI step.

Baselines: tools/tracecheck_baseline.json, tools/meshcheck_baseline.json.
Exit codes: 0 clean, 1 new findings (either suite), 2 usage/parse errors.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ANALYSIS_DIR = os.path.join(REPO, "paddle_tpu", "analysis")

SUITES = ("tracecheck", "meshcheck")


def _load_analysis():
    """Import paddle_tpu.analysis WITHOUT triggering the framework's
    top-level __init__ (which pulls in jax).  Loaded as the standalone
    package ``ptanalysis`` so the suites' relative imports
    (``from ..tracecheck import ...``) resolve."""
    spec = importlib.util.spec_from_file_location(
        "ptanalysis", os.path.join(ANALYSIS_DIR, "__init__.py"),
        submodule_search_locations=[ANALYSIS_DIR])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["ptanalysis"] = mod
    spec.loader.exec_module(mod)
    import importlib as _il
    return (_il.import_module("ptanalysis.tracecheck"),
            _il.import_module("ptanalysis.meshcheck"))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="analyze",
        description="Run the tracecheck (TRC) + meshcheck (MSH) static "
                    "analyzers over one AST parse.")
    p.add_argument("path", nargs="?",
                   default=os.path.join(REPO, "paddle_tpu"),
                   help="package directory (or single file) to analyze")
    p.add_argument("--suite", choices=("all",) + SUITES, default="all")
    p.add_argument("--json", action="store_true", dest="as_json")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore baselines: report every finding")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the selected suites' baselines from "
                        "current findings")
    p.add_argument("--rules", default=None,
                   help="comma-separated subset of rules (TRC00x/MSH00x; "
                        "each suite picks out its own)")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--stats", action="store_true")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    tc, mc = _load_analysis()

    if args.list_rules:
        for code in sorted(tc.RULES):
            print(f"{code}: {tc.RULES[code]}")
        for code in sorted(mc.MESH_RULES):
            print(f"{code}: {mc.MESH_RULES[code]}")
        return 0
    if not os.path.exists(args.path):
        print(f"analyze: no such path: {args.path}", file=sys.stderr)
        return 2

    suites = SUITES if args.suite == "all" else (args.suite,)
    wanted = None
    if args.rules:
        if args.update_baseline:
            # a rule-filtered run sees a subset of findings; writing it
            # out would erase every unselected rule's baseline entries
            print("analyze: --rules cannot be combined with "
                  "--update-baseline (it would clobber the other "
                  "rules' baseline entries)", file=sys.stderr)
            return 2
        wanted = {r.strip().upper() for r in args.rules.split(",")
                  if r.strip()}

    t0 = time.time()
    parsed = tc.parse_package(args.path)
    for err in parsed.errors:
        print(f"analyze: parse error: {err}", file=sys.stderr)
    if parsed.errors:
        # an unparseable file would silently shrink coverage — a gate
        # that cannot see the whole package must not pass
        return 2

    parent = os.path.dirname(os.path.abspath(args.path.rstrip(os.sep)))
    baseline_paths = {
        "tracecheck": os.path.join(parent, "tools",
                                   "tracecheck_baseline.json"),
        "meshcheck": os.path.join(parent, "tools",
                                  "meshcheck_baseline.json"),
    }

    payload = {}
    any_new = False
    for suite in suites:
        pkg = tc if suite == "tracecheck" else mc
        config = pkg.AnalyzerConfig()
        if wanted is not None:
            sub = tuple(r for r in config.rules if r in wanted)
            if not sub:
                continue
            config = pkg.AnalyzerConfig(rules=sub)
        result = pkg.analyze_package(args.path, config, parsed=parsed)

        bl_path = baseline_paths[suite]
        if args.update_baseline:
            entries = pkg.write_baseline(bl_path, result.findings)
            print(f"{suite}: baselined {len(entries)} finding(s) -> "
                  f"{bl_path}")
            continue
        baseline = (pkg.load_baseline(bl_path)
                    if not args.no_baseline else None)
        if baseline:
            new, leftovers = pkg.subtract_baseline(result.findings,
                                                   baseline)
            n_baselined = len(result.findings) - len(new)
        else:
            new, leftovers, n_baselined = result.findings, {}, 0
        any_new = any_new or bool(new)

        payload[suite] = {
            "findings": [f.to_json() for f in new],
            "baselined": n_baselined,
            "suppressed": len(result.suppressed),
            "stale_baseline_entries": sorted(leftovers),
        }
        if not args.as_json:
            for f in new:
                print(f.format())
            summary = (f"{suite}: {len(new)} new finding(s), "
                       f"{n_baselined} baselined, "
                       f"{len(result.suppressed)} pragma-suppressed")
            if leftovers:
                summary += (f"; {sum(leftovers.values())} stale "
                            "baseline entr(ies) — run --update-baseline")
            print(summary)

    elapsed = time.time() - t0
    if args.update_baseline:
        return 0
    if args.as_json:
        payload["files"] = parsed.n_files
        payload["elapsed_s"] = round(elapsed, 3)
        print(json.dumps(payload, indent=1, sort_keys=True))
    elif args.stats:
        print(f"-- {parsed.n_files} files, one parse, "
              f"{len(suites)} suite(s) in {elapsed:.2f}s")
    return 1 if any_new else 0


if __name__ == "__main__":
    sys.exit(main())
