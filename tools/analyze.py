#!/usr/bin/env python
"""Unified static-analysis gate: tracecheck + meshcheck + faultcheck +
kernelcheck + statecheck + keycheck in ONE parse.

Usage:
    python tools/analyze.py                      # all six suites, gate
    python tools/analyze.py --suite keycheck     # one suite
    python tools/analyze.py --format json        # (--json still works)
    python tools/analyze.py --format sarif       # CI code-scanning upload
    python tools/analyze.py --format github      # ::error annotations
    python tools/analyze.py --changed-only       # git-diff-scoped report
    python tools/analyze.py --update-baseline    # rewrites ALL baselines
    python tools/analyze.py --list-rules

The package is parsed ONCE (ast.parse dominates analyzer wall clock);
all suites consume the same ParsedPackage, so the combined tier-1 gate
stays inside the r08 ~15 s budget.  Pure AST — the analysis package is
loaded standalone (never through ``paddle_tpu/__init__``), so no jax
import, no device; safe as a pre-commit hook or bare CI step.

``--changed-only`` still parses and analyzes the WHOLE package (the
call graph, donor propagation and SPMD/recovery contexts need every
module) but reports only findings in files the git working tree changed
vs HEAD (staged, unstaged, or untracked) — the fast pre-push loop.
Stale-baseline reporting is suppressed in that mode: an entry for an
unchanged file is filtered, not stale.

Baselines: tools/{tracecheck,meshcheck,faultcheck,kernelcheck,
statecheck,keycheck}_baseline.json.
Exit codes: 0 clean, 1 new findings (any suite), 2 usage/parse errors.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ANALYSIS_DIR = os.path.join(REPO, "paddle_tpu", "analysis")

SUITES = ("tracecheck", "meshcheck", "faultcheck", "kernelcheck",
          "statecheck", "keycheck")
FORMATS = ("human", "json", "sarif", "github")

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def _load_analysis():
    """Import paddle_tpu.analysis WITHOUT triggering the framework's
    top-level __init__ (which pulls in jax).  Loaded as the standalone
    package ``ptanalysis`` so the suites' relative imports
    (``from ..tracecheck import ...``) resolve."""
    spec = importlib.util.spec_from_file_location(
        "ptanalysis", os.path.join(ANALYSIS_DIR, "__init__.py"),
        submodule_search_locations=[ANALYSIS_DIR])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["ptanalysis"] = mod
    spec.loader.exec_module(mod)
    import importlib as _il
    return {s: _il.import_module(f"ptanalysis.{s}") for s in SUITES}


def _rule_catalogue(pkg):
    for attr in ("RULES", "MESH_RULES", "FAULT_RULES", "KERNEL_RULES",
                 "STATE_RULES", "KEY_RULES"):
        cat = getattr(pkg, attr, None)
        if cat:
            return cat
    return {}


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="analyze",
        description="Run the tracecheck (TRC) + meshcheck (MSH) + "
                    "faultcheck (FLT) + kernelcheck (KRN) + "
                    "statecheck (STC) + keycheck (KEY) static "
                    "analyzers over one AST parse.")
    p.add_argument("path", nargs="?",
                   default=os.path.join(REPO, "paddle_tpu"),
                   help="package directory (or single file) to analyze")
    p.add_argument("--suite", choices=("all",) + SUITES, default="all")
    p.add_argument("--format", choices=FORMATS, default=None,
                   dest="fmt",
                   help="output format: human (default), json, sarif "
                        "(2.1.0 — CI code-scanning upload), github "
                        "(::error workflow annotations)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="alias for --format json")
    p.add_argument("--changed-only", action="store_true",
                   help="report only findings in files changed vs git "
                        "HEAD (staged/unstaged/untracked); the whole "
                        "package is still parsed for context")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore baselines: report every finding")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the selected suites' baselines from "
                        "current findings")
    p.add_argument("--rules", default=None,
                   help="comma-separated subset of rules (TRC00x/MSH00x/"
                        "FLT00x/KRN00x/STC00x/KEY00x; each suite picks "
                        "out its own)")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--stats", action="store_true")
    return p


def _changed_files(repo_hint: str, findings_base: str):
    """Paths the working tree changed vs HEAD (plus untracked files),
    rebased onto ``findings_base`` — the directory findings' paths are
    relative to — so the filter matches regardless of where the git
    root sits relative to the analyzed package (or single file).
    Raises CalledProcessError on a non-repo."""
    def git(cwd, *args):
        out = subprocess.run(["git", "-C", cwd] + list(args),
                             capture_output=True, text=True, check=True)
        return [l.strip() for l in out.stdout.splitlines() if l.strip()]

    # resolve the toplevel first: `diff --name-only` is root-relative
    # from any cwd, while `ls-files --others` is cwd-relative — running
    # both AT the toplevel makes every name root-relative
    top = git(repo_hint, "rev-parse", "--show-toplevel")[0]
    names = git(top, "diff", "--name-only", "HEAD")
    names += git(top, "ls-files", "--others", "--exclude-standard")
    changed = set()
    for n in names:
        rel = os.path.relpath(os.path.join(top, n), findings_base)
        if not rel.startswith(".."):
            changed.add(rel.replace(os.sep, "/"))
    return changed


def _to_sarif(per_suite, catalogues) -> dict:
    rules, results = [], []
    seen_rules = set()
    for suite, payload in per_suite.items():
        cat = catalogues.get(suite, {})
        for f in payload["findings"]:
            rid = f["rule"]
            if rid not in seen_rules:
                seen_rules.add(rid)
                rules.append({
                    "id": rid,
                    "shortDescription": {
                        "text": cat.get(rid, rid)[:200]},
                })
            results.append({
                "ruleId": rid,
                "level": "error",
                "message": {"text": f["message"]},
                "partialFingerprints": {
                    "fingerprint/v1": f["fingerprint"]},
                "locations": [{
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": f["path"],
                            "uriBaseId": "SRCROOT"},
                        "region": {"startLine": f["line"]},
                    }}],
            })
    return {
        "version": SARIF_VERSION,
        "$schema": SARIF_SCHEMA,
        "runs": [{
            "tool": {"driver": {
                "name": "analyze",
                "informationUri": "tools/analyze.py (tracecheck+"
                    "meshcheck+faultcheck+kernelcheck+statecheck+"
                    "keycheck)",
                "rules": sorted(rules, key=lambda r: r["id"]),
            }},
            "results": results,
        }],
    }


def _emit_github(per_suite) -> None:
    for suite in sorted(per_suite):
        for f in per_suite[suite]["findings"]:
            msg = f["message"].replace("%", "%25").replace(
                "\r", "").replace("\n", "%0A")
            print(f"::error file={f['path']},line={f['line']},"
                  f"title={f['rule']}::{msg}")


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    fmt = args.fmt or ("json" if args.as_json else "human")
    pkgs = _load_analysis()

    if args.list_rules:
        for suite in SUITES:
            cat = _rule_catalogue(pkgs[suite])
            for code in sorted(cat):
                print(f"{code}: {cat[code]}")
        return 0
    if not os.path.exists(args.path):
        print(f"analyze: no such path: {args.path}", file=sys.stderr)
        return 2

    suites = SUITES if args.suite == "all" else (args.suite,)
    wanted = None
    if args.rules:
        if args.update_baseline:
            # a rule-filtered run sees a subset of findings; writing it
            # out would erase every unselected rule's baseline entries
            print("analyze: --rules cannot be combined with "
                  "--update-baseline (it would clobber the other "
                  "rules' baseline entries)", file=sys.stderr)
            return 2
        wanted = {r.strip().upper() for r in args.rules.split(",")
                  if r.strip()}

    changed = None
    if args.changed_only:
        if args.update_baseline:
            # same clobber argument one level up: a diff-scoped run
            # sees a subset of files, and writing its findings out
            # would erase every unchanged file's baseline entries
            print("analyze: --changed-only cannot be combined with "
                  "--update-baseline (it would clobber unchanged "
                  "files' baseline entries)", file=sys.stderr)
            return 2
        p = os.path.abspath(args.path.rstrip(os.sep))
        # findings' paths are relative to the package's PARENT — for a
        # single-file target that is the file's grandparent (the file's
        # own directory is the package), mirroring parse_package
        findings_base = (os.path.dirname(os.path.dirname(p))
                         if os.path.isfile(p) else os.path.dirname(p))
        try:
            changed = _changed_files(
                p if os.path.isdir(p) else os.path.dirname(p),
                findings_base)
        except (subprocess.CalledProcessError, FileNotFoundError) as e:
            print(f"analyze: --changed-only needs a git checkout "
                  f"({e})", file=sys.stderr)
            return 2

    t0 = time.time()
    tc = pkgs["tracecheck"]
    parsed = tc.parse_package(args.path)
    for err in parsed.errors:
        print(f"analyze: parse error: {err}", file=sys.stderr)
    if parsed.errors:
        # an unparseable file would silently shrink coverage — a gate
        # that cannot see the whole package must not pass
        return 2

    parent = os.path.dirname(os.path.abspath(args.path.rstrip(os.sep)))
    baseline_paths = {
        s: os.path.join(parent, "tools", f"{s}_baseline.json")
        for s in SUITES}

    payload = {}
    catalogues = {}
    any_new = False
    for suite in suites:
        pkg = pkgs[suite]
        catalogues[suite] = _rule_catalogue(pkg)
        config = pkg.AnalyzerConfig()
        if wanted is not None:
            sub = tuple(r for r in config.rules if r in wanted)
            if not sub:
                continue
            config = pkg.AnalyzerConfig(rules=sub)
        result = pkg.analyze_package(args.path, config, parsed=parsed)

        findings = result.findings
        if changed is not None:
            findings = [f for f in findings if f.path in changed]

        bl_path = baseline_paths[suite]
        if args.update_baseline:
            entries = pkg.write_baseline(bl_path, findings)
            print(f"{suite}: baselined {len(entries)} finding(s) -> "
                  f"{bl_path}")
            continue
        baseline = (pkg.load_baseline(bl_path)
                    if not args.no_baseline else None)
        if baseline:
            new, leftovers = pkg.subtract_baseline(findings, baseline)
            n_baselined = len(findings) - len(new)
            if changed is not None:
                leftovers = {}      # filtered != stale
        else:
            new, leftovers, n_baselined = findings, {}, 0
        any_new = any_new or bool(new)

        payload[suite] = {
            "findings": [f.to_json() for f in new],
            "baselined": n_baselined,
            "suppressed": len(result.suppressed),
            "stale_baseline_entries": sorted(leftovers),
        }
        if fmt == "human":
            for f in new:
                print(f.format())
            summary = (f"{suite}: {len(new)} new finding(s), "
                       f"{n_baselined} baselined, "
                       f"{len(result.suppressed)} pragma-suppressed")
            if changed is not None:
                summary += f" (changed-only: {len(changed)} file(s))"
            if leftovers:
                summary += (f"; {sum(leftovers.values())} stale "
                            "baseline entr(ies) — run --update-baseline")
            print(summary)

    elapsed = time.time() - t0
    if args.update_baseline:
        return 0
    if fmt == "json":
        payload["files"] = parsed.n_files
        payload["elapsed_s"] = round(elapsed, 3)
        print(json.dumps(payload, indent=1, sort_keys=True))
    elif fmt == "sarif":
        print(json.dumps(_to_sarif(payload, catalogues), indent=1,
                         sort_keys=True))
    elif fmt == "github":
        _emit_github(payload)
    elif args.stats:
        print(f"-- {parsed.n_files} files, one parse, "
              f"{len(suites)} suite(s) in {elapsed:.2f}s")
    return 1 if any_new else 0


if __name__ == "__main__":
    sys.exit(main())
