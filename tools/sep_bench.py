"""Ring vs Ulysses sequence/context parallelism: comm-volume analysis +
measured step time on the 8-device CPU mesh. Writes SEQUENCE_PARALLEL.md
(VERDICT r2 item 10 — the decision rule for `sep` users).

Run on TPU (ambient backend) for on-chip numbers; CPU mesh otherwise.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import toolenv  # noqa: E402

toolenv.force_cpu(devices=8)

import numpy as np  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def comm_table():
    """Per-shard bytes SENT per attention layer, forward pass, bf16.
    Ring: K and V chunks rotate P-1 times -> 2 * (P-1) * B*(S/P)*Hkv*D*2.
    Ulysses: 2 all_to_alls (q,k,v gather + out scatter = 4 arrays), each
    sending (P-1)/P of the local shard -> 4 * (P-1)/P * B*(S/P)*H*D*2.
    (Backward doubles both; constants cancel in the ratio.)"""
    rows = []
    B, D = 1, 128
    for S in (32768, 131072):
        for P_ in (4, 8, 16):
            for H, Hkv in ((32, 32), (64, 8)):
                ring = 2 * (P_ - 1) * B * (S // P_) * Hkv * D * 2
                uly = 4 * (P_ - 1) / P_ * B * (S // P_) * H * D * 2
                rows.append((S, P_, H, Hkv, ring / 1e6, uly / 1e6,
                             ring / uly))
    return rows


def measure(method, S, P_=8, B=1, H=8, D=64, steps=3):
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from paddle_tpu.distributed.fleet.utils.ring_flash_attention import (
        sep_scaled_dot_product_attention)

    mesh = Mesh(np.array(jax.devices()[:P_]), ("sep",))
    rng = np.random.default_rng(0)
    sh = NamedSharding(mesh, P(None, "sep", None, None))
    mk = lambda: jax.device_put(
        jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.bfloat16), sh)
    q, k, v = mk(), mk(), mk()

    def loss(q, k, v):
        return sep_scaled_dot_product_attention(
            q, k, v, mesh=mesh, method=method).astype(jnp.float32).sum()

    g = jax.jit(lambda q, k, v: sum(
        t.astype(jnp.float32).sum()
        for t in jax.grad(loss, argnums=(0, 1, 2))(q, k, v)))
    float(g(q, k, v))          # compile
    ts = []
    for _ in range(steps):
        t0 = time.perf_counter()
        float(g(q, k, v))
        ts.append(time.perf_counter() - t0)
    return sorted(ts)[len(ts) // 2]


def main():
    backend = jax.default_backend()
    meas = []
    for S in (4096, 8192):
        tr = measure("ring", S)
        tu = measure("ulysses", S)
        meas.append((S, tr, tu))
        print(f"S={S}: ring {tr*1e3:.0f} ms, ulysses {tu*1e3:.0f} ms",
              file=sys.stderr)
    # 32k+ is not measurable on the CPU mesh: ulysses' dense inner
    # materializes (S, S) f32 per head (OOMs host RAM), and ring's 32k
    # step exceeds XLA-CPU's fixed 40 s collective-permute rendezvous
    # timeout (one straggler host thread aborts the program). The 4k->8k
    # scaling plus the analytic comm table below cover the long-context
    # regime; rerun on a TPU slice for on-chip numbers.

    lines = [
        "# Sequence/context parallelism: ring vs Ulysses",
        "",
        "Decision guidance for `sep_scaled_dot_product_attention(..., "
        "method=)` (`ring_flash_attention.py`). Reference axes: the "
        "reference's sep_degree (Ulysses) and out-of-tree balanced ring "
        "flash attention — SURVEY.md §5.7.",
        "",
        "## Communication volume (per shard, per layer, fwd, bf16)",
        "",
        "Ring rotates the K/V chunks around the full ring; Ulysses "
        "all-to-alls q/k/v to head sharding and the output back:",
        "",
        "| S | P | H | Hkv | ring MB | ulysses MB | ring/ulysses |",
        "|---|---|---|---|---|---|---|",
    ]
    for S, P_, H, Hkv, r, u, ratio in comm_table():
        lines.append(f"| {S//1024}k | {P_} | {H} | {Hkv} | {r:.1f} | "
                     f"{u:.1f} | {ratio:.1f}x |")
    lines += [
        "",
        "Closed form: ring/ulysses = P * Hkv / (2 H). Ulysses sends less "
        "whenever P > 2*H/Hkv — i.e. almost always for MHA (Hkv = H), and "
        "for GQA once P exceeds twice the group count.",
        "",
        f"## Measured fwd+bwd step time ({backend} backend, 8-way sep, "
        "B=1 H=8 D=64)",
        "",
        "| S | ring | ulysses |",
        "|---|---|---|",
    ] + [f"| {S//1024}k | {tr*1e3:.0f} ms | {tu*1e3:.0f} ms |"
         for S, tr, tu in meas] + [
        "",
        "32k+ is not measurable on the host mesh (ulysses' dense inner "
        "OOMs RAM; ring trips XLA-CPU's 40 s collective rendezvous "
        "timeout). The analytic table above covers the long-context "
        "regime; on TPU the flash kernel drops into ulysses via "
        "`attn_fn` and ring's per-step blocks stay VMEM-sized.",
        "",
        "## Decision rule",
        "",
        "- **Ulysses first** when P divides the Q head count: fewest "
        "bytes, one hop, and the inner attention is a plain "
        "single-device kernel (the Pallas flash kernel drops in via "
        "`attn_fn`). GQA with Hkv < P is handled too: kv heads are "
        "all-gathered in sequence instead of head-split (comm 2 q "
        "all-to-alls + one kv all-gather — cheaper than ring whenever "
        "Hkv <= 2H/P).",
        "- **Ring** when P exceeds the q head count, or when "
        "nearest-neighbour-only comm matters (ICI torus without "
        "all-to-all bandwidth): its per-step ppermute overlaps with the "
        "block matmuls, and its causal load-balancing favors very "
        "long S.",
        "- Both compose with dp/mp/pp on the same mesh "
        "(`sep_scaled_dot_product_attention` shard_maps only the sep "
        "axis; everything else stays GSPMD).",
        "",
        "CPU-mesh times measure schedule+comm structure, not MXU math; "
        "re-run this tool on a TPU slice for on-chip numbers "
        "(`python tools/sep_bench.py` with the ambient backend).",
        "",
    ]
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "SEQUENCE_PARALLEL.md")
    with open(out, "w") as f:
        f.write("\n".join(lines))
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
