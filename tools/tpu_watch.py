"""TPU health watcher: bank on-chip bench artifacts the moment a healthy
window opens (VERDICT round-2 item 1 — chip-health windows are a perishable
resource).

Runs as a long-lived background process (tmux). Loop:
  1. probe `jax.devices()` in a subprocess with a timeout (the axon tunnel
     can hang PJRT init indefinitely — never probe in-process);
  2. on a healthy probe, run each missing bench artifact in its own
     subprocess (generous timeout; persistent XLA compilation cache so a
     short window still amortizes compiles across runs);
  3. git-commit each artifact the moment it lands (bank incrementally —
     the window may close mid-sequence);
  4. sleep and re-probe.

Exits when every artifact is banked. Round 4 on: the banking sequence
lives in tools/chip_sprint.py (strict leverage order — kernel compile
checks, attn/rmsnorm microbenches, 345M MFU + decode); the watcher just
probes and arms the sprint, which banks + commits per step itself.
"""
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
import bench as bench_mod  # shared probe + cache-env logic (single source)

LOG = os.path.join(REPO, ".cache", "tpu_watch.log")


def log(msg: str) -> None:
    line = f"[{time.strftime('%H:%M:%S')}] {msg}"
    print(line, flush=True)
    os.makedirs(os.path.dirname(LOG), exist_ok=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def base_env() -> dict:
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # ambient = TPU via the axon tunnel
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return bench_mod.cache_env(env)


# single source: bench.py owns the socket pre-check (its own probe loop
# now runs it too), the watcher just aliases it
relay_listening = bench_mod.relay_listening


def probe() -> str:
    """'tpu' | 'cpu' | 'dead' | 'no-relay' — one check per loop iteration
    so the backoff branch can't disagree with a re-check."""
    if not relay_listening():
        log("probe -> no-relay (:8083 refused — skipped 150 s PJRT probe)")
        return "no-relay"
    state = bench_mod._probe_backend(base_env())
    log(f"probe -> {state}")
    return state


ROUND = os.environ.get("CHIP_SPRINT_ROUND", "r05")
ARTIFACTS = [f"KERNEL_COMPILE_{ROUND}.json", f"ATTN_BENCH_{ROUND}.json",
             f"RMSNORM_BENCH_{ROUND}.json", f"BENCH_tpu_{ROUND}.json",
             f"SD_BENCH_{ROUND}.json", f"PROFILE_{ROUND}.json",
             f"TRAIN_TUNE_{ROUND}.json", f"DECODE7B_{ROUND}.json"]


def run_sprint() -> None:
    """Arm tools/chip_sprint.py: it banks + commits each step itself and
    skips already-banked artifacts, so re-arming after a flap is safe."""
    env = base_env()
    env["CHIP_SPRINT_ROUND"] = ROUND   # single source: sprint banks the
    r = subprocess.run(                # same artifact names we wait for
        [sys.executable, os.path.join(REPO, "tools", "chip_sprint.py")],
        env=env, capture_output=True, text=True, timeout=4 * 3600,
        cwd=REPO)
    log(f"chip_sprint rc={r.returncode} tail={r.stdout[-400:]} "
        f"stderr={r.stderr[-400:]}")


def main() -> None:
    os.makedirs(base_env()["JAX_COMPILATION_CACHE_DIR"], exist_ok=True)
    deadline = time.time() + float(os.environ.get("TPU_WATCH_HOURS", "11")) * 3600
    interval = 120.0
    while time.time() < deadline:
        try:                    # the sprint owns the failed-check retry
            with open(os.path.join(REPO, ".cache",       # bound; read its
                                   "sprint_retries.json")) as f:  # ledger
                retries = json.load(f)
        except (OSError, ValueError):
            retries = {}
        # mirror chip_sprint.run_step exactly via the shared artifact_state:
        # 'stale_schema' is ALWAYS todo (the sprint bypasses the retry
        # ledger for it); the ledger only parks 'failed_checks' artifacts
        todo = []
        for p in ARTIFACTS:
            st = bench_mod.artifact_state(os.path.join(REPO, p))
            if st == "banked":
                continue
            # ledger >= 2 means the sprint will PARK this artifact on its
            # next attempt (_bump_retry pre-bump bound) — arming another
            # sprint for it alone would only bump the counter
            if st == "failed_checks" and retries.get(p, 0) >= 2:
                continue
            todo.append(p)
        if not todo:
            log("all artifacts banked (or retries exhausted) — exiting")
            return
        state = probe()
        if state == "tpu":
            interval = 120.0
            try:
                run_sprint()
            except Exception as e:
                log(f"sprint FAILED: {e!r}"[:500])
        elif state == "no-relay":
            interval = 60.0   # socket pre-check is ~free; poll often
        else:
            interval = min(interval * 1.5, 600.0)
        time.sleep(interval)
    log("watch window over")


if __name__ == "__main__":
    main()
