"""TPU health watcher: bank on-chip bench artifacts the moment a healthy
window opens (VERDICT round-2 item 1 — chip-health windows are a perishable
resource).

Runs as a long-lived background process (tmux). Loop:
  1. probe `jax.devices()` in a subprocess with a timeout (the axon tunnel
     can hang PJRT init indefinitely — never probe in-process);
  2. on a healthy probe, run each missing bench artifact in its own
     subprocess (generous timeout; persistent XLA compilation cache so a
     short window still amortizes compiles across runs);
  3. git-commit each artifact the moment it lands (bank incrementally —
     the window may close mid-sequence);
  4. sleep and re-probe.

Exits when every artifact is banked. Artifacts (repo root):
  ATTN_BENCH_r03.json     flash-vs-dense fwd+bwd at 1k/2k/4k/8k
  RMSNORM_BENCH_r03.json  pallas-vs-XLA rms_norm
  BENCH_tpu_r03.json      real gpt345m MFU via bench.py on the chip
"""
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
import bench as bench_mod  # shared probe + cache-env logic (single source)

LOG = os.path.join(REPO, ".cache", "tpu_watch.log")


def log(msg: str) -> None:
    line = f"[{time.strftime('%H:%M:%S')}] {msg}"
    print(line, flush=True)
    os.makedirs(os.path.dirname(LOG), exist_ok=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def base_env() -> dict:
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # ambient = TPU via the axon tunnel
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return bench_mod.cache_env(env)


def probe() -> bool:
    state = bench_mod._probe_backend(base_env())
    log(f"probe -> {state}")
    return state == "tpu"


def run_json_lines(argv, timeout: int, env=None) -> list:
    """Run a bench subprocess; return its stdout JSON lines. Raises on
    nonzero rc (a partial run must NOT be banked as a complete artifact)
    or when no line parses."""
    r = subprocess.run(argv, env=env or base_env(), capture_output=True,
                       text=True, timeout=timeout, cwd=REPO)
    lines = []
    for ln in r.stdout.splitlines():
        try:
            lines.append(json.loads(ln))
        except (json.JSONDecodeError, ValueError):
            continue
    if r.returncode != 0 or not lines:
        raise RuntimeError(f"rc={r.returncode} lines={len(lines)} "
                           f"stderr={r.stderr[-2000:]}")
    return lines


def require_tpu(lines: list) -> None:
    """Every bench line self-reports its backend; refuse to bank anything
    that silently fell back to CPU between probe and run."""
    bad = [l.get("backend") for l in lines
           if l.get("backend") not in ("tpu", "axon")]
    if bad:
        raise RuntimeError(f"bench ran on {bad[0]!r}, not TPU — not banking")


def commit(path: str, msg: str) -> None:
    for attempt in range(5):  # index.lock races with the main session
        r = subprocess.run(["git", "add", path], cwd=REPO,
                           capture_output=True, text=True)
        if r.returncode == 0:
            r = subprocess.run(["git", "commit", "-m", msg, "--", path],
                               cwd=REPO, capture_output=True, text=True)
            if r.returncode == 0:
                log(f"committed {path}")
                return
        log(f"commit attempt {attempt}: {r.stderr.strip()[:200]}")
        time.sleep(10)
    log(f"GAVE UP committing {path} — left in working tree")


def bank_attn() -> None:
    lines = run_json_lines(
        [sys.executable, os.path.join(REPO, "tools", "attn_bench.py")],
        timeout=3600)
    require_tpu(lines)
    out = {"backend": lines[-1]["backend"],
           "ts": time.strftime("%Y-%m-%dT%H:%M:%S"), "results": lines}
    p = os.path.join(REPO, "ATTN_BENCH_r03.json")
    with open(p, "w") as f:
        json.dump(out, f, indent=1)
    commit(p, "Bank on-chip flash-vs-dense attention bench (r3)")


def bank_rmsnorm() -> None:
    lines = run_json_lines(
        [sys.executable, os.path.join(REPO, "tools", "rmsnorm_bench.py")],
        timeout=1800)
    require_tpu(lines)
    out = {"backend": lines[-1]["backend"],
           "ts": time.strftime("%Y-%m-%dT%H:%M:%S"), "results": lines}
    p = os.path.join(REPO, "RMSNORM_BENCH_r03.json")
    with open(p, "w") as f:
        json.dump(out, f, indent=1)
    commit(p, "Bank on-chip rms_norm pallas-vs-XLA bench (r3)")


def bank_gpt345m() -> None:
    env = base_env()
    env["BENCH_TIMEOUT"] = "3000"
    # The watcher just probed: cap bench.py's own probe backoff so the
    # outer timeout (3300) > probe budget (60) + child budget (3000).
    env["BENCH_PROBE_BUDGET"] = "60"
    lines = run_json_lines([sys.executable, os.path.join(REPO, "bench.py")],
                           timeout=3300, env=env)
    res = lines[-1]
    if res.get("backend") not in ("tpu", "axon") or "fallback" in res:
        raise RuntimeError(f"bench fell back to {res.get('backend')}")
    p = os.path.join(REPO, "BENCH_tpu_r03.json")
    with open(p, "w") as f:
        json.dump(res, f, indent=1)
    commit(p, "Bank on-chip gpt345m MFU bench (r3)")


ARTIFACTS = [
    ("ATTN_BENCH_r03.json", bank_attn),
    ("RMSNORM_BENCH_r03.json", bank_rmsnorm),
    ("BENCH_tpu_r03.json", bank_gpt345m),
]


def main() -> None:
    os.makedirs(base_env()["JAX_COMPILATION_CACHE_DIR"], exist_ok=True)
    deadline = time.time() + float(os.environ.get("TPU_WATCH_HOURS", "11")) * 3600
    interval = 120.0
    while time.time() < deadline:
        todo = [(p, fn) for p, fn in ARTIFACTS
                if not os.path.exists(os.path.join(REPO, p))]
        if not todo:
            log("all artifacts banked — exiting")
            return
        if probe():
            interval = 120.0
            for p, fn in todo:
                try:
                    log(f"running {p} ...")
                    fn()
                except Exception as e:
                    log(f"{p} FAILED: {e!r}"[:500])
                    break  # window may have closed; re-probe
        else:
            interval = min(interval * 1.5, 600.0)
        time.sleep(interval)
    log("watch window over")


if __name__ == "__main__":
    main()
