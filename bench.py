#!/usr/bin/env python
"""Benchmark driver: trains the GPT-3 345M smoke config (BASELINE.json
configs[0]) with the jitted train step on the available device and prints ONE
JSON line: {"metric", "value", "unit", "vs_baseline"}.

vs_baseline is MFU / 0.45 — the fraction of the 45%-MFU north-star target
(BASELINE.md; no reference-published numbers exist to compare against).

Env knobs: BENCH_MODEL (gpt345m|gpt_tiny|llama_tiny), BENCH_STEPS,
BENCH_BATCH, BENCH_SEQ.
"""

import json
import os
import sys


def main():
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.hapi import TrainStep
    from paddle_tpu.models import GPTConfig, GPTForCausalLM, LlamaConfig, LlamaForCausalLM
    from paddle_tpu.utils.metrics import SpeedMeter

    import jax

    model_name = os.environ.get("BENCH_MODEL", "gpt345m")
    steps = int(os.environ.get("BENCH_STEPS", "12"))
    on_tpu = jax.default_backend() in ("tpu", "axon")

    if model_name == "gpt345m":
        cfg = GPTConfig.gpt3_345m()
        batch = int(os.environ.get("BENCH_BATCH", "8"))
        seq = int(os.environ.get("BENCH_SEQ", "1024"))
        model_cls = GPTForCausalLM
    elif model_name == "gpt_tiny":
        cfg = GPTConfig.tiny()
        batch = int(os.environ.get("BENCH_BATCH", "4"))
        seq = int(os.environ.get("BENCH_SEQ", "64"))
        model_cls = GPTForCausalLM
    else:
        cfg = LlamaConfig.tiny()
        batch = int(os.environ.get("BENCH_BATCH", "4"))
        seq = int(os.environ.get("BENCH_SEQ", "64"))
        model_cls = LlamaForCausalLM

    paddle.seed(0)
    model = model_cls(cfg)
    n_params = sum(p.size for p in model.parameters())
    if on_tpu:
        # bf16 params + fp32 master weights: the TPU-native training recipe
        model.to(dtype="bfloat16")
    opt = paddle.optimizer.AdamW(
        1e-4, parameters=model.parameters(), weight_decay=0.01,
        multi_precision=on_tpu)
    step = TrainStep(model, opt)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch, seq + 1))
    x = paddle.to_tensor(ids[:, :-1].astype(np.int32))
    y = paddle.to_tensor(ids[:, 1:].astype(np.int32))

    meter = SpeedMeter(
        n_params=n_params, n_layers=cfg.num_hidden_layers,
        hidden=cfg.hidden_size, seq_len=seq,
        n_chips=jax.device_count(), warmup=2)

    import jax.numpy as jnp
    first_loss = last_loss = None
    meter.start()
    for i in range(steps):
        with paddle.amp.auto_cast(enable=on_tpu, level="O1", dtype="bfloat16"):
            loss = step(x, y)
        jax.block_until_ready(loss.value)
        meter.step(batch * seq)
        if i == 0:
            first_loss = float(loss)
        last_loss = float(loss)

    s = meter.summary()
    result = {
        "metric": f"{model_name}_mfu",
        "value": round(s["mfu"], 4),
        "unit": "mfu_fraction",
        "vs_baseline": round(s["mfu"] / 0.45, 4),
        "tokens_per_sec_per_chip": round(s["tokens_per_sec_per_chip"], 1),
        "median_step_time_s": round(s["median_step_time_s"], 4),
        "n_params": n_params,
        "first_loss": round(first_loss, 4),
        "last_loss": round(last_loss, 4),
        "backend": jax.default_backend(),
        "n_chips": jax.device_count(),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
