#!/usr/bin/env python
"""Benchmark driver: trains the GPT-3 345M smoke config (BASELINE.json
configs[0]) with the jitted train step on the available device and prints ONE
JSON line: {"metric", "value", "unit", "vs_baseline"}.

vs_baseline is MFU / 0.45 — the fraction of the 45%-MFU north-star target
(BASELINE.md; no reference-published numbers exist to compare against).

Robustness contract (VERDICT.md round-1 item 1b): the ambient TPU backend can
hang or fail at PJRT init. The parent process therefore never touches jax —
it probes backend health in a subprocess with a timeout (retrying once), then
re-execs itself as a child either on the ambient backend (healthy) or on
forced CPU with a clearly labeled fallback marker. Whatever happens, exactly
one JSON line is printed to stdout.

Env knobs: BENCH_MODEL (gpt345m|gpt_tiny|llama_tiny), BENCH_STEPS,
BENCH_BATCH, BENCH_SEQ.
"""

import json
import os
import subprocess
import sys
from typing import Optional

_PROBE = "import jax; d = jax.devices(); print(len(d), jax.default_backend())"


def cache_env(env: dict) -> dict:
    """Persistent XLA compilation cache: one healthy window amortizes
    compiles across bench runs and the tpu_watch harness."""
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".cache", "xla"))
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    return env


# bump when the measurement itself improves (not when numbers move):
# sprint re-banks artifacts recorded under an older schema on the next
# healthy window. 2 = pipelined steady-state window + batched decode +
# flash 512x512 defaults (the r05 mid-round tuning). 3 = the benched
# program changed underneath the banked artifact (flash dispatch at seq
# 1024 + bf16 residual stream — see flags.py flash_attn_min_seqlen and
# amp/auto_cast.py BLACK_LIST): the schema-2 number measured the dense
# f32-stream step, which no longer exists; manual on-chip A/B of the new
# step is banked in TRAIN_AB_r05.json (mfu 0.3909 -> 0.4627).
BENCH_SCHEMA = 3
# same idea for the kernel-compile artifact: bump when NEW kernels join
# the check list (2 = + paged/block-table decode attention)
# (3 = + SD-UNet head shapes d=40/80/160 non-causal: the
# flash_attn_min_seqlen 1024 flip routes them through the kernel)
# (4 = + fused_block_decode, the whole-layer serving kernel — its
# Mosaic compile status gates nothing yet [jnp fallback serves CPU and
# the flag is the rollback] but must be PROVEN before trusting the
# fused TPU numbers)
KERNELS_SCHEMA = 4


def build_train_setup(model_name: Optional[str] = None):
    """Single source of the bench's model/optimizer/TrainStep recipe.
    tools/train_profile.py reuses it so the profiled step IS the
    benchmarked step (same dtype policy, weight decay, master weights).
    Returns (cfg, batch, seq, build, on_tpu) with ``build(remat) ->
    (model, TrainStep)``."""
    import paddle_tpu as paddle
    from paddle_tpu.flags import is_tpu_backend
    from paddle_tpu.hapi import TrainStep
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM, LlamaConfig,
                                   LlamaForCausalLM)

    if model_name is None:
        model_name = os.environ.get("BENCH_MODEL", "gpt345m")
    on_tpu = is_tpu_backend()
    if model_name == "gpt345m":
        cfg = GPTConfig.gpt3_345m()
        batch = int(os.environ.get("BENCH_BATCH", "8"))
        seq = int(os.environ.get("BENCH_SEQ", "1024"))
        model_cls = GPTForCausalLM
    elif model_name == "gpt_tiny":
        cfg = GPTConfig.tiny()
        batch = int(os.environ.get("BENCH_BATCH", "4"))
        seq = int(os.environ.get("BENCH_SEQ", "64"))
        model_cls = GPTForCausalLM
    else:
        cfg = LlamaConfig.tiny()
        batch = int(os.environ.get("BENCH_BATCH", "4"))
        seq = int(os.environ.get("BENCH_SEQ", "64"))
        model_cls = LlamaForCausalLM

    def build(remat: bool):
        paddle.seed(0)
        model = model_cls(cfg)
        if on_tpu:
            # bf16 params + fp32 master weights: the TPU training recipe
            model.to(dtype="bfloat16")
        opt = paddle.optimizer.AdamW(
            1e-4, parameters=model.parameters(), weight_decay=0.01,
            multi_precision=on_tpu)
        return model, TrainStep(model, opt, remat=remat)

    return cfg, batch, seq, build, on_tpu


def artifact_state(path: str) -> str:
    """Why an artifact is or is not banked — shared by chip_sprint
    (skip/re-run/retry decision) and tpu_watch (exit decision) so they
    can't diverge. Returns one of:
      'banked'        exists, parses, zero failed checks, current schema
      'missing'       absent or unparseable
      'failed_checks' recorded per-check failures (bounded retries)
      'stale_schema'  measured under an older schema (always re-run on a
                      healthy window; train re-benches on BENCH_SCHEMA
                      bumps, kernels re-compiles on KERNELS_SCHEMA bumps)
    """
    if not os.path.exists(path):
        return "missing"
    try:
        with open(path) as f:
            d = json.load(f)
    except (OSError, ValueError):
        return "missing"
    if d.get("n_failed_checks", 0) != 0:
        return "failed_checks"
    recs = d.get("results", [])
    schema = max([r.get("bench_schema", 1) for r in recs] or [1])
    # sd joins at BENCH_SCHEMA 3: the flash_attn_min_seqlen flip changed
    # the UNet's seq-1024 attention program under the banked number
    current = {"train": BENCH_SCHEMA, "kernels": KERNELS_SCHEMA,
               "sd": BENCH_SCHEMA}
    if schema < current.get(d.get("step"), 1):
        return "stale_schema"
    return "banked"


def artifact_banked(path: str) -> bool:
    return artifact_state(path) == "banked"


def _tpu_expected(env: dict) -> bool:
    """Whether this machine should have a TPU (the axon tunnel plugin is
    configured). Decides if a clean CPU-backend probe means 'no chip here'
    (definitive) or 'plugin failed init during a flap' (retry)."""
    return ("PALLAS_AXON_POOL_IPS" in env
            or env.get("BENCH_EXPECT_TPU", "") == "1")


def relay_listening(timeout: float = 3.0) -> bool:
    """Cheap socket pre-check (TUNNEL_DIAGNOSIS.md): under the loopback
    relay (``AXON_LOOPBACK_RELAY=1``), ``jax.devices()`` goes via the
    relay's :8083 stateless endpoint. Connection refused means no relay
    process exists — a 150 s PJRT probe would only hang in the claim
    loop, so skip it and poll again soon. Environments NOT behind the
    relay (or with a non-default port — set ``AXON_RELAY_PORT``) always
    fall through to the real probe. Shared with tools/tpu_watch.py (one
    pre-check, one diagnosis)."""
    if os.environ.get("AXON_LOOPBACK_RELAY") != "1":
        return True   # no relay in the path; only the PJRT probe can tell
    port = int(os.environ.get("AXON_RELAY_PORT", "8083"))
    import socket
    try:
        with socket.create_connection(("127.0.0.1", port), timeout=timeout):
            return True
    except OSError:
        return False


def _probe_backend(env: dict, timeout: int = 150) -> str:
    """Returns 'tpu' (healthy chip), 'cpu' (clean exit on a CPU backend —
    jax silently fell back), or 'dead' (hang or crash — the tunnel-flap
    failure mode)."""
    try:
        r = subprocess.run([sys.executable, "-c", _PROBE], env=env,
                           capture_output=True, text=True, timeout=timeout)
    except (subprocess.TimeoutExpired, OSError):
        return "dead"
    if r.returncode != 0:
        return "dead"
    return "tpu" if ("tpu" in r.stdout or "axon" in r.stdout) else "cpu"


def _probe_with_backoff(env: dict) -> str:
    """Wait for a healthy tunnel window (VERDICT r05 #1: the official
    number must land on chip — falling back to CPU at the first sick
    probe burned every round so far). On an expected-TPU machine the
    budget is 35 min (BENCH_PROBE_BUDGET overrides); machines without a
    TPU resolve on the first clean CPU probe. Each iteration runs the
    relay socket pre-check first — 'no relay process' is knowable in 3 s,
    so the 150 s PJRT probe is only spent when a relay is listening —
    and polls fast (20 s) while the relay is down, slower (45 s) after a
    failed real probe. Returns 'tpu', 'cpu' (no TPU here — definitive),
    or 'dead' (budget exhausted on an expected-but-unhealthy chip)."""
    import time
    expected = _tpu_expected(env)
    budget = float(os.environ.get("BENCH_PROBE_BUDGET",
                                  "2100" if expected else "600"))
    deadline = time.time() + budget
    while True:
        if not relay_listening():
            state = "dead"   # no relay process: PJRT would hang, skip it
            wait = 20.0
        else:
            state = _probe_backend(env)
            wait = 45.0
        if state == "tpu" or (state == "cpu" and not expected):
            return state
        if time.time() + wait >= deadline:
            return state
        sys.stderr.write(f"bench: TPU probe unhealthy ({state}), "
                         f"retrying in {wait:.0f}s "
                         f"({deadline - time.time():.0f}s left)...\n")
        time.sleep(wait)


def _parent() -> int:
    """Probe backend health, then run the bench in a child process and
    forward its one JSON line. Always prints one JSON line itself on any
    failure mode."""
    # Probe unless explicitly pinned to CPU: even with JAX_PLATFORMS unset,
    # the axon sitecustomize registers a TPU backend whose init can hang.
    state = "tpu"
    if os.environ.get("JAX_PLATFORMS", "") != "cpu":
        state = _probe_with_backoff(dict(os.environ))

    env = cache_env(dict(os.environ))
    env["_PADDLE_TPU_BENCH_CHILD"] = "1"
    if state != "tpu":
        env["JAX_PLATFORMS"] = "cpu"
        # distinct labels: flaky chip vs a machine with no chip at all.
        # On an expected-TPU machine even a clean 'cpu' probe is a flap
        # (the plugin can fail init cleanly), never "no chip here".
        env["_PADDLE_TPU_BENCH_FALLBACK"] = (
            "tpu_backend_unhealthy"
            if (state == "dead" or _tpu_expected(dict(os.environ)))
            else "no_tpu_backend")
        # CPU cannot train 345M in reasonable time; shrink unless pinned.
        env.setdefault("BENCH_MODEL", "gpt_tiny")
    if env.get("JAX_PLATFORMS", "") == "cpu":
        # the axon plugin can hang at import even when jax is pinned to cpu
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("PALLAS_AXON_REMOTE_COMPILE", None)

    try:
        r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                           env=env, capture_output=True, text=True,
                           timeout=int(os.environ.get("BENCH_TIMEOUT", "1500")))
    except subprocess.TimeoutExpired as e:
        tail = ((e.stderr or b"")[-800:] if isinstance(e.stderr, bytes)
                else (e.stderr or "")[-800:])
        print(json.dumps({"metric": "bench_error", "value": 0.0,
                          "unit": "error", "vs_baseline": 0.0,
                          "error": f"bench child timed out: {tail}"}))
        return 0

    sys.stderr.write(r.stderr[-4000:])
    # Forward the child's JSON line (last stdout line that parses as JSON).
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            json.loads(line)
        except (json.JSONDecodeError, ValueError):
            continue
        print(line)
        return 0
    print(json.dumps({"metric": "bench_error", "value": 0.0,
                      "unit": "error", "vs_baseline": 0.0,
                      "error": f"child rc={r.returncode}: "
                               f"{(r.stderr or r.stdout)[-800:]}"}))
    return 0


def _run_bench() -> dict:
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.utils.metrics import SpeedMeter

    import jax

    model_name = os.environ.get("BENCH_MODEL", "gpt345m")
    steps = int(os.environ.get("BENCH_STEPS", "12"))
    cfg, batch, seq, build, on_tpu = build_train_setup(model_name)
    remat = os.environ.get("BENCH_REMAT", "0") == "1"
    model, step = build(remat)
    n_params = sum(p.size for p in model.parameters())

    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch, seq + 1))
    x = paddle.to_tensor(ids[:, :-1].astype(np.int32))
    y = paddle.to_tensor(ids[:, 1:].astype(np.int32))

    if not remat:
        # HBM insurance for the rare healthy chip window (VERDICT r4 #2):
        # if the no-remat step OOMs, fall back to remat instead of losing
        # the round's only real-MFU shot. Snapshot state first so the
        # measured run restarts from step 0 WITHOUT a second compile of
        # the big program (set_state_dict reuses the jitted step), and
        # sync via a host pull — block_until_ready does not reliably
        # block (or raise) through the axon tunnel.
        # deep-copy to host: state_dict's Tensors alias the on-device
        # buffers the probe step is about to donate
        snap = {k: (np.array(v.numpy(), copy=True)
                    if hasattr(v, "numpy") else v)
                for k, v in step.state_dict().items()}
        try:
            float(step(x, y))
        except Exception as e:
            if "RESOURCE_EXHAUSTED" not in repr(e).upper():
                raise
            sys.stderr.write("bench: no-remat step OOMed; retrying with "
                             "remat\n")
            remat = True
            model, step = build(remat)
        else:
            step.set_state_dict(snap)

    meter = SpeedMeter(
        n_params=n_params, n_layers=cfg.num_hidden_layers,
        hidden=cfg.hidden_size, seq_len=seq,
        n_chips=jax.device_count(), warmup=2)

    first_loss = last_loss = None
    meter.start()
    for i in range(steps):
        with paddle.amp.auto_cast(enable=on_tpu, level="O1", dtype="bfloat16"):
            step(x, y)
        # the trainer's metrics_every=1 arm: a per-step host pull (this
        # is the synced A/B side; it also keeps the in-flight window
        # drained, so the throttle counter stays a pure health probe)
        last_loss = step.pull_metrics(lag=0)["loss"]
        meter.step(batch * seq)
        if i == 0:
            first_loss = last_loss

    s = meter.summary()

    # Steady-state pipelined window — the TRAINER'S OWN async loop, not a
    # hand-rolled one: TrainStep.__call__ never blocks on the loss and
    # step.sync() is the same hard barrier Model.fit runs at epoch end
    # (hapi/train_step.py). The per-step float() above pays a full host
    # round-trip per step — through the axon tunnel that RTT is charged
    # to every step and is not a cost of the framework. If dispatch is
    # truly synchronous on this backend the two numbers coincide; when
    # they diverge the pipelined one is the honest device throughput.
    import time as _time
    pipe_steps = int(os.environ.get("BENCH_PIPE_STEPS", str(max(8, steps))))
    pipe_tps = 0.0
    try:
        assert pipe_steps <= step.max_in_flight, \
            "window would throttle; raise FLAGS_train_max_in_flight"
        with paddle.amp.auto_cast(enable=on_tpu, level="O1", dtype="bfloat16"):
            step(x, y)
            step.sync()            # rejoin the pipeline before timing
            t0 = _time.perf_counter()
            for _ in range(pipe_steps):
                step(x, y)
            step.sync()            # closes the pipeline (NOT last_loss:
            # the banked last_loss stays "after `steps` measured steps",
            # comparable across schema versions)
            pipe_elapsed = _time.perf_counter() - t0
        pipe_tps = pipe_steps * batch * seq / pipe_elapsed / max(
            jax.device_count(), 1)
    except Exception as e:   # best-effort window: the synced numbers above
        s["pipelined_error"] = repr(e)[:200]   # are already complete
    synced_tps = s["tokens_per_sec_per_chip"]
    if synced_tps > 0 and pipe_tps > synced_tps:
        # median_step_time_s stays the per-step-synced MEDIAN (robust,
        # comparable across rounds); the pipelined figure is a mean over
        # the window and gets its own key
        s["tokens_per_sec_synced"] = round(synced_tps, 1)
        s["mfu_synced"] = round(s["mfu"], 4)
        s["mfu"] = s["mfu"] * pipe_tps / synced_tps
        s["tokens_per_sec_per_chip"] = pipe_tps
        s["pipelined_step_time_s"] = round(pipe_elapsed / pipe_steps, 4)
    result = {
        "metric": f"{model_name}_mfu",
        "value": round(s["mfu"], 4),
        "unit": "mfu_fraction",
        "vs_baseline": round(s["mfu"] / 0.45, 4),
        "tokens_per_sec_per_chip": round(s["tokens_per_sec_per_chip"], 1),
        "median_step_time_s": round(s["median_step_time_s"], 4),
        "n_params": n_params,
        "first_loss": round(first_loss, 4),
        "last_loss": round(last_loss, 4),
        "backend": jax.default_backend(),
        "n_chips": jax.device_count(),
        "remat": remat,
        # probe-visible loop health: one trace for the whole run and the
        # async window's host syncs (the throttle counter must stay 0)
        "step_traces": step.trace_count,
        "step_throttles": step.throttle_count,
        "bench_schema": BENCH_SCHEMA,
    }
    if "mfu_synced" in s:
        result["mfu_synced"] = s["mfu_synced"]
        result["tokens_per_sec_synced"] = s["tokens_per_sec_synced"]
        result["pipelined_step_time_s"] = s["pipelined_step_time_s"]
    if "pipelined_error" in s:
        result["pipelined_error"] = s["pipelined_error"]
    fallback = os.environ.get("_PADDLE_TPU_BENCH_FALLBACK")
    if fallback:
        # MFU against a nominal CPU peak is meaningless (VERDICT r2 weak
        # #4): report throughput as the headline and null out the MFU.
        result["fallback"] = fallback
        result["vs_baseline"] = 0.0
        result["mfu"] = None
        result["metric"] = f"{model_name}_tokens_per_sec_cpu_fallback"
        result["value"] = result["tokens_per_sec_per_chip"]
        result["unit"] = "tokens_per_sec_per_chip"
    if os.environ.get("BENCH_DECODE", "1") == "1":
        try:
            step.sync_to_model()  # training donated the old param buffers
            result.update(_decode_bench(model, cfg, paddle, jax))
        except Exception as e:  # decode bench is best-effort extra signal
            result["decode_error"] = repr(e)[:200]
    if os.environ.get("BENCH_SD", "1" if on_tpu else "0") == "1":
        # free the GPT training state first: SD15 + AdamW master weights
        # plus the 345M train state would overrun one chip's HBM (the
        # optimizer state lives inside the TrainStep's donated buffers)
        del step, model
        try:
            result.update(_sd_unet_bench(paddle, jax, on_tpu))
        except Exception as e:  # best-effort extra signal
            result["sd_error"] = repr(e)[:200]
    # embed the telemetry snapshot: every banked perf row carries its own
    # retrace / cache-hit / sync-count evidence (tools/telemetry_dump.py
    # renders it back)
    try:
        from paddle_tpu import observability as _obs
        if _obs.enabled():
            result["telemetry"] = _obs.registry().snapshot()
            # memwatch section: per-program compiled memory + watermarks
            # (the on-chip re-bank sprint captures memory for free;
            # telemetry_dump --memory renders it back)
            result["memory"] = _obs.memory.section()
    except Exception as e:  # best-effort extra signal
        result["telemetry_error"] = repr(e)[:200]
    return result


def _sd_unet_bench(paddle, jax, on_tpu) -> dict:
    """SD-1.x UNet denoising train step: imgs/sec/chip (BASELINE configs[4],
    'to measure' — this sets the number)."""
    import time

    import numpy as np

    from paddle_tpu.hapi import TrainStep
    from paddle_tpu.models import (UNet2DConditionModel, UNetConfig,
                                   UNetDenoiseLoss)

    paddle.seed(0)
    cfg = (UNetConfig.sd15() if on_tpu else UNetConfig.tiny())
    # batch 4 OOMs HBM on one v5e (r05 sprint, activation temps); start at
    # the known-fitting 2 so a tunnel window is spent compiling ONE program
    batch = int(os.environ.get("BENCH_SD_BATCH", "2"))
    steps = int(os.environ.get("BENCH_SD_STEPS", "8"))
    model = UNet2DConditionModel(cfg)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    if on_tpu:
        model.to(dtype="bfloat16")
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters(),
                                 multi_precision=on_tpu)
    # remat: SD15 + AdamW master weights is HBM-tight on one v5e chip
    step = TrainStep(UNetDenoiseLoss(model), opt, remat=on_tpu)
    rng = np.random.default_rng(0)
    dt = "bfloat16" if on_tpu else "float32"

    def _run(batch):
        lat = paddle.to_tensor(rng.standard_normal(
            (batch, cfg.in_channels, cfg.sample_size, cfg.sample_size)
        ).astype(np.float32)).astype(dt)
        t = paddle.to_tensor(rng.integers(0, 1000, (batch,)).astype(np.int32))
        ctx = paddle.to_tensor(rng.standard_normal(
            (batch, 77, cfg.cross_attention_dim)).astype(np.float32)).astype(dt)
        noise = paddle.to_tensor(rng.standard_normal(
            lat.shape).astype(np.float32)).astype(dt)
        loss = step(lat, t, ctx, noise)  # compile
        float(loss)  # host sync (block_until_ready unreliable on the tunnel)
        times = []
        last = None
        for _ in range(steps):
            t0 = time.perf_counter()
            last = step(lat, t, ctx, noise)
            float(last)
            times.append(time.perf_counter() - t0)
        return sorted(times)[len(times) // 2], last

    # SD15 + AdamW is right at the v5e HBM edge (r05: batch=4 OOMed in
    # activation temps); step down the batch until the step fits rather
    # than losing the artifact
    oom_fallbacks = 0
    while True:
        try:
            med, last = _run(batch)
            break
        except Exception as e:
            if batch > 1 and ("RESOURCE_EXHAUSTED" in repr(e)
                              or "out of memory" in repr(e).lower()):
                batch //= 2
                oom_fallbacks += 1
                continue
            raise
    # unsharded step: runs on ONE device regardless of slice size
    return {
        "sd_unet_imgs_per_sec_per_chip": round(batch / med, 2),
        "sd_unet_step_time_s": round(med, 4),
        "sd_unet_batch": batch,
        "sd_unet_oom_fallbacks": oom_fallbacks,
        "sd_unet_n_params": n_params,
        "sd_unet_loss": round(float(last), 4),
    }


def _decode_bench(model, cfg, paddle, jax) -> dict:
    """Decode tokens/sec on the same model via the generate() path."""
    import time

    import numpy as np

    if not hasattr(model, "generate"):
        return {}
    steps = int(os.environ.get("BENCH_DECODE_TOKENS", "64"))
    # prompt + new tokens sized so the KV cache length is a multiple of
    # 128 on TPU: the flash_prefill kernel then serves the prefill phase
    # (odd cache lengths fall back to the dense einsum path)
    default_prompt = ((-steps) % 128) or 128
    if default_prompt < 16:
        default_prompt += 128          # keep prompt+steps on a 128 multiple
    prompt_len = int(os.environ.get("BENCH_PROMPT_LEN",
                                    str(default_prompt)))
    rng = np.random.default_rng(0)
    prompt = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (1, prompt_len)).astype(np.int32))
    model.eval()

    def timed(n_tokens, repeats=3, prompt=prompt):
        # warmup MUST use the same max_new_tokens: the jit signature
        # includes the scan length, so a different value compiles a
        # different program and the timed run would measure compilation
        out = model.generate(prompt, max_new_tokens=n_tokens,
                             do_sample=False)
        np.asarray(out.value if hasattr(out, "value") else out)  # host
        # sync: block_until_ready is unreliable through the axon tunnel
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            out = model.generate(prompt, max_new_tokens=n_tokens,
                                 do_sample=False)
            np.asarray(out.value if hasattr(out, "value") else out)
            best = min(best, time.perf_counter() - t0)
        return best

    # steady-state decode rate: subtract the prefill(+1 token) time so
    # the metric is not a function of the prompt length (the r2->r3
    # redefinition artifact VERDICT r4 weak #1 flagged); keep the
    # end-to-end number too for continuity
    t_full = timed(steps)
    t_one = timed(1)
    out = {"decode_e2e_tokens_per_sec": round(steps / t_full, 1),
           "prefill_plus_1_s": round(t_one, 4)}
    dt = t_full - t_one
    if dt > 0.05 * t_full:
        out["decode_tokens_per_sec"] = round((steps - 1) / dt, 1)
    else:
        # timing noise swamped the decode segment — flag, don't fabricate
        out["decode_tokens_per_sec"] = None
        out["decode_note"] = ("prefill dominated the measurement "
                              f"(t_full={t_full:.4f}s ~ t_one={t_one:.4f}s)"
                              "; steady-state rate not identifiable")

    # Serving throughput: single-stream decode is HBM-bound at ~1 token
    # per full weight read (the r05 on-chip number sits at that roofline);
    # batching amortizes the weight read across streams. Costs two extra
    # compiles, so it is skippable with BENCH_DECODE_BATCH=0, and its
    # failures must not cost the single-stream numbers already in `out`.
    dbatch = int(os.environ.get("BENCH_DECODE_BATCH", "8"))
    if dbatch > 1:
        try:
            prompt_b = paddle.to_tensor(
                rng.integers(0, cfg.vocab_size,
                             (dbatch, prompt_len)).astype(np.int32))
            tb_full = timed(steps, prompt=prompt_b)
            tb_one = timed(1, prompt=prompt_b)
            dtb = tb_full - tb_one
            if dtb > 0.05 * tb_full:
                out["decode_batch"] = dbatch
                out["decode_batched_tokens_per_sec"] = round(
                    dbatch * (steps - 1) / dtb, 1)
        except Exception as e:  # best-effort extra signal
            out["decode_batched_error"] = repr(e)[:200]

    # Fused block decode A/B: the serving engine's steady-state step with
    # the fused one-kernel-per-layer program (FLAGS_fused_block_decode,
    # kernels/fused_block_decode.py) vs the generic op-chain step.
    # Models without block_decode_spec (GPT family) skip — the dedicated
    # tools/fused_decode_bench.py carries the full A/B either way.
    if (os.environ.get("BENCH_DECODE_FUSED", "1") == "1"
            and hasattr(model, "block_decode_spec")):
        try:
            from paddle_tpu import flags as _flags
            from paddle_tpu.generation.program_cache import \
                decode_program_cache
            from paddle_tpu.generation.serving import ServingEngine

            fb, fsteps = min(4, max(dbatch, 1)), 16
            fprompts = [rng.integers(0, cfg.vocab_size, (prompt_len,))
                        .astype(np.int32) for _ in range(fb)]
            fpage = 64 if prompt_len + fsteps > 128 else 8
            fmsl = prompt_len + fsteps + fpage

            def serving_step_s(fused):
                _flags.set_flags({"fused_block_decode": fused})
                eng = ServingEngine(model, max_batch=fb, page_size=fpage,
                                    max_seq_len=fmsl)
                for p in fprompts:
                    eng.submit(p, fsteps)
                eng.step()          # prefills + first decode (compiles)
                n = 0
                t0 = time.perf_counter()
                while eng.has_work():
                    eng.step()
                    n += 1
                dt = (time.perf_counter() - t0) / max(n, 1)
                return dt, decode_program_cache().trace_count(
                    eng.decode_key)

            prior = _flags.get_flag("fused_block_decode")
            try:
                tf, fused_traces = serving_step_s(True)
                tu, _ = serving_step_s(False)
            finally:
                _flags.set_flags({"fused_block_decode": prior})
            out["decode_fused_step_ms"] = round(tf * 1000, 3)
            out["decode_unfused_step_ms"] = round(tu * 1000, 3)
            if tf > 0:
                out["decode_fused_speedup"] = round(tu / tf, 3)
            out["decode_fused_traces"] = fused_traces
        except Exception as e:  # best-effort extra signal
            out["decode_fused_error"] = repr(e)[:200]

    # Weight-only int8 serving: decode is weight-bandwidth-bound (the
    # bf16 single-stream number sits AT the HBM roofline), so halving
    # weight bytes should move the roofline itself. Quantizes the model
    # IN PLACE — this block must stay the last user of `model`.
    if os.environ.get("BENCH_DECODE_QUANT", "1") == "1":
        try:
            from paddle_tpu.nn.quant import quantize_linears
            quantize_linears(model, algo="weight_only_int8")
            tq_full = timed(steps)
            tq_one = timed(1)
            dtq = tq_full - tq_one
            if dtq > 0.05 * tq_full:
                out["decode_tokens_per_sec_int8"] = round(
                    (steps - 1) / dtq, 1)
            else:
                out["decode_tokens_per_sec_int8"] = None
                out["decode_int8_note"] = (
                    "prefill dominated the measurement; steady-state "
                    "int8 rate not identifiable")
        except Exception as e:  # best-effort extra signal
            out["decode_int8_error"] = repr(e)[:200]
    return out


def main():
    if os.environ.get("_PADDLE_TPU_BENCH_CHILD") != "1":
        sys.exit(_parent())
    try:
        result = _run_bench()
    except Exception as e:
        import traceback
        tail = traceback.format_exc()[-800:]
        result = {"metric": "bench_error", "value": 0.0, "unit": "error",
                  "vs_baseline": 0.0, "error": f"{e!r}: {tail}"}
    print(json.dumps(result))


if __name__ == "__main__":
    main()
